#!/usr/bin/env bash
# CI gate: tier-1 tests, then the perf-regression sentinel against the
# committed BENCH_*.json baselines.  A perf regression fails the build
# instead of only being reportable.
#
# Usage:
#   tools/ci_check.sh                    # tier-1 + sentinel over --sentinel
#   CI_BENCH_LEGS="--sentinel --obs" tools/ci_check.sh
#   CI_SKIP_TESTS=1 tools/ci_check.sh   # sentinel only (tests ran already)
#
# Each leg in CI_BENCH_LEGS is re-run into a scratch dir (via the
# BLAZE_BENCH_<LEG>_PATH override every leg honors) and compared
# per-artifact against the committed baseline of the same name — the
# whole committed directory is NOT used as one baseline, because a
# candidate that regenerates only some legs would fail --ci's
# missing-metric check for the rest.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export BLAZE_BENCH_PLATFORM="${BLAZE_BENCH_PLATFORM:-cpu}"

if [ "${CI_SKIP_TESTS:-0}" != "1" ]; then
    echo "== ci_check: tier-1 tests =="
    python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider
fi

LEGS="${CI_BENCH_LEGS:---sentinel}"
WORK="$(mktemp -d /tmp/blaze-ci-check.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT

# Fast AQE smoke (CI_AQE_FAST=0 to skip): the adaptive-execution test
# module plus a 1-rep skew-leg-only bench run.  --fast emits a reduced
# artifact into scratch and self-gates on its own exit code (skew
# speedup + zero divergence); it is NOT sentinel-compared because the
# reduced artifact carries fewer metrics than the committed baseline.
if [ "${CI_AQE_FAST:-1}" = "1" ]; then
    echo "== ci_check: AQE tests =="
    python -m pytest tests/test_adaptive.py -q -p no:cacheprovider
    echo "== ci_check: bench --aqe --fast (smoke) =="
    env "BLAZE_BENCH_AQE_PATH=$WORK/BENCH_AQE_FAST.json" \
        python bench.py --aqe --fast
fi

# Fast multichip/overlap smoke (CI_MULTICHIP_FAST=0 to skip): the
# overlapped-exchange test module plus a reduced --multichip run —
# 1- and 2-device legs, small per-worker shards, one probe query.
# Self-gating: bench --multichip exits nonzero on a non-monotone
# curve, any sync-vs-overlap divergence, or a barrier-idle reduction
# below the 30% floor.  Not sentinel-compared (reduced legs carry
# fewer metrics than the committed BENCH_SF100 baseline).
if [ "${CI_MULTICHIP_FAST:-1}" = "1" ]; then
    echo "== ci_check: overlapped-exchange tests =="
    python -m pytest tests/test_exchange_overlap.py -q -p no:cacheprovider
    echo "== ci_check: bench --multichip (overlap smoke) =="
    env "BLAZE_BENCH_SF100_PATH=$WORK/BENCH_SF100_FAST.json" \
        BLAZE_BENCH_MULTICHIP_DEVICES=1,2 \
        BLAZE_BENCH_MULTICHIP_ROWS=65536 \
        BLAZE_BENCH_MULTICHIP_REPS=2 \
        BLAZE_BENCH_MULTICHIP_WAVES=2 \
        BLAZE_BENCH_MULTICHIP_QUERIES=q06 \
        BLAZE_BENCH_MULTICHIP_SCALE=0.05 \
        BLAZE_BENCH_MULTICHIP_PROBE_SCALE=0.05 \
        python bench.py --multichip
fi

# Fast fleet smoke (CI_FLEET_FAST=0 to skip): the fleet test module
# plus a reduced --fleet run — a 2-replica loopback fleet over the
# shared socket RSS tier with one seeded mid-run SIGKILL.  Self-gating:
# bench --fleet exits nonzero on any lost query, divergent result,
# duplicate committed block, or a per-replica history rollup that does
# not sum to the completed total.  Not sentinel-compared (the reduced
# artifact carries fewer queries than the committed BENCH_FLEET
# baseline).
if [ "${CI_FLEET_FAST:-1}" = "1" ]; then
    echo "== ci_check: fleet tests =="
    python -m pytest tests/test_fleet.py -q -p no:cacheprovider
    echo "== ci_check: bench --fleet --fast (kill-replica smoke) =="
    env "BLAZE_BENCH_FLEET_PATH=$WORK/BENCH_FLEET_FAST.json" \
        python bench.py --fleet --fast
fi

# Fast encodings smoke (CI_ENCODINGS_FAST=0 to skip): the dictionary-
# string and decimal-lane test modules plus a reduced --encodings run —
# string-group-by and decimal-agg legs, encodings off vs on.  Self-
# gating: bench --encodings exits nonzero on any divergent frame, a
# leg that stays host-placed with the encodings on, any device-lane
# fallback, or an eviction fraction that fails to drop.  Not
# sentinel-compared (the reduced corpus carries different walls than
# the committed BENCH_ENCODINGS baseline).
if [ "${CI_ENCODINGS_FAST:-1}" = "1" ]; then
    echo "== ci_check: encoding-lane tests =="
    python -m pytest tests/test_dict_strings.py tests/test_decimal_lanes.py \
        -q -p no:cacheprovider
    echo "== ci_check: bench --encodings --fast (smoke) =="
    env "BLAZE_BENCH_ENCODINGS_PATH=$WORK/BENCH_ENCODINGS_FAST.json" \
        python bench.py --encodings --fast
fi

fail=0
for leg in $LEGS; do
    name="$(echo "${leg#--}" | tr '[:lower:]' '[:upper:]')"
    art="BENCH_${name}.json"
    if [ ! -f "$art" ]; then
        echo "ci_check: no committed baseline $art for $leg" >&2
        fail=1
        continue
    fi
    echo "== ci_check: bench $leg (candidate -> $WORK/$art) =="
    env "BLAZE_BENCH_${name}_PATH=$WORK/$art" python bench.py "$leg"
    echo "== ci_check: sentinel --ci ($art) =="
    if ! python -m blaze_tpu.tools.sentinel --ci \
            --baseline "$art" --candidate "$WORK/$art"; then
        fail=1
    fi
done

if [ "$fail" != "0" ]; then
    echo "ci_check: FAILED" >&2
    exit 1
fi
echo "ci_check: OK"
