"""Cross-process shuffle tests: TWO real CPU processes exchange
.data/.index files through HostShuffleService, each writing its map
outputs and reducing its assigned partitions (VERDICT r1 #10; the
BlockManager/RSS transport analog)."""

import json
import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

pytestmark = pytest.mark.dist  # deselect with -m 'not dist'

WORKER = r"""
import json, os, sys
# The axon TPU plugin ignores the JAX_PLATFORMS env var (see conftest.py);
# the override must go through jax.config before first backend use.
import jax
jax.config.update("jax_platforms", "cpu")
import pyarrow as pa
import pyarrow.parquet as pq
import blaze_tpu
from blaze_tpu.memory import MemManager
from blaze_tpu.parallel.distributed import HostShuffleService
from blaze_tpu.plan import create_plan
from blaze_tpu.shuffle.exchange import read_index_file

cfg = json.loads(sys.argv[1])
MemManager.init(4 << 30)
svc = HostShuffleService(cfg["root"], cfg["shuffle_id"],
                         num_maps=cfg["num_maps"],
                         num_reduces=cfg["num_reduces"])

# ---- map side: this process owns one map task ----
map_id = cfg["process_id"]
data, index = svc.map_output_paths(map_id)
plan = {
    "kind": "shuffle_writer",
    "partitioning": {"kind": "hash",
                     "exprs": [{"kind": "column", "index": 0}],
                     "num_partitions": cfg["num_reduces"]},
    "data_file": data, "index_file": index,
    "input": {"kind": "hash_agg",
              "groupings": [{"expr": {"kind": "column", "name": "k"},
                             "name": "k"}],
              "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                        "args": [{"kind": "column", "name": "v"}]}],
              "input": {"kind": "parquet_scan",
                        "schema": {"fields": [
                            {"name": "k", "type": {"id": "int64"},
                             "nullable": True},
                            {"name": "v", "type": {"id": "float64"},
                             "nullable": True}]},
                        "file_groups": [[cfg["input"]]]}}}
p = create_plan(plan)
for _ in p.execute(0):
    pass
svc.commit_map(map_id)

# ---- reduce side: wait for ALL processes' maps, reduce our partition ----
svc.wait_for_maps(timeout_s=90)
rid = f"xproc-{cfg['shuffle_id']}"
svc.register_reader(rid)
reduce_id = cfg["process_id"]
final = {
    "kind": "hash_agg",
    "groupings": [{"expr": {"kind": "column", "index": 0}, "name": "k"}],
    "aggs": [{"fn": "sum", "mode": "final", "name": "s",
              "args": [{"kind": "column", "index": 1}]}],
    "input": {"kind": "ipc_reader", "resource_id": rid,
              "schema": {"fields": [
                  {"name": "k", "type": {"id": "int64"},
                   "nullable": True},
                  {"name": "s.sum", "type": {"id": "float64"},
                   "nullable": True}]},
              "num_partitions": cfg["num_reduces"]}}
fp = create_plan(final)
out = [b.compact().to_arrow() for b in fp.execute(reduce_id)]
out = [b for b in out if b.num_rows]
tbl = (pa.Table.from_batches(out) if out
       else pa.table({"k": pa.array([], type=pa.int64()),
                      "s": pa.array([], type=pa.float64())}))
pq.write_table(tbl, cfg["result"])
print("OK", tbl.num_rows)
"""


def test_two_processes_exchange_shuffle_files(tmp_path):
    import pyarrow.parquet as pq
    rng = np.random.default_rng(0)
    n = 20_000
    t = pa.table({"k": pa.array(rng.integers(0, 300, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    # each process scans its own half of the input (its "executor split")
    half = n // 2
    inputs = []
    for i, sl in enumerate((t.slice(0, half), t.slice(half))):
        p = str(tmp_path / f"input-{i}.parquet")
        pq.write_table(sl, p)
        inputs.append(p)

    root = str(tmp_path / "exchange")
    procs = []
    results = [str(tmp_path / f"result-{i}.parquet") for i in range(2)]
    for pid in range(2):
        cfg = {"root": root, "shuffle_id": "t1", "num_maps": 2,
               "num_reduces": 2, "process_id": pid,
               "input": inputs[pid], "result": results[pid]}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER, json.dumps(cfg)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            cwd=os.path.dirname(os.path.dirname(__file__))))
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
            assert out.decode().startswith("OK")
    finally:
        for p in procs:  # never orphan a hung worker
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)

    got = pa.concat_tables([pq.read_table(r) for r in results]).to_pandas()
    want = t.to_pandas().groupby("k", as_index=False).v.sum()
    got = got.sort_values("k").reset_index(drop=True)
    want = want.sort_values("k").reset_index(drop=True)
    assert len(got) == len(want)
    # every key must land in exactly one reducer
    assert got.k.is_unique
    np.testing.assert_allclose(got["s"].to_numpy(), want.v.to_numpy(),
                               rtol=1e-9)


def test_wait_for_maps_times_out(tmp_path):
    from blaze_tpu.parallel.distributed import HostShuffleService
    svc = HostShuffleService(str(tmp_path), "never", num_maps=1,
                             num_reduces=1)
    with pytest.raises(TimeoutError):
        svc.wait_for_maps(timeout_s=0.2, poll_s=0.05)


def test_init_distributed_smoke():
    """jax.distributed bootstrap in a subprocess (single-process world:
    the multi-host path with num_processes=1)."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from blaze_tpu.parallel.distributed import init_distributed\n"
        "n = init_distributed('127.0.0.1:12355', 1, 0)\n"
        "print('DEVICES', n)\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       timeout=120,
                       cwd=os.path.dirname(os.path.dirname(__file__)))
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"DEVICES" in r.stdout
