"""Raw-row ICI exchange + the operators built on it (distributed sort,
shuffled hash join) on the 8-virtual-device CPU mesh.

Parity target: the reference's repartitioner moves arbitrary operator
output (shuffle/mod.rs:55-123), feeding range-partitioned global sort
(NativeShuffleExchangeBase.scala:313) and the shuffled hash join
(joins/join_hash_map.rs).  These tests check the on-mesh equivalents end
to end: multiset preservation, global ordering, and exact inner-join
results against a numpy oracle, with nulls and duplicate keys present.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blaze_tpu.parallel import (DP_AXIS, all_to_all_rows,
                                distributed_hash_join, distributed_sort,
                                make_mesh, shard_rows)
from blaze_tpu.parallel.mesh import shard_map_compat
from jax.sharding import PartitionSpec as P

NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < NDEV:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(NDEV)


def test_all_to_all_rows_roundtrip(mesh):
    rng = np.random.default_rng(7)
    rows_per_dev = 512
    n = NDEV * rows_per_dev
    keys = rng.integers(0, 1000, n).astype(np.int64)
    vals = rng.random(n)
    valid = rng.random(n) < 0.85
    pid = (keys % NDEV).astype(np.int32)
    cap = 2 * rows_per_dev

    def stage(k, v, ok, p):
        cols, valid_r, ovf = all_to_all_rows([k, v], ok, p, DP_AXIS,
                                             NDEV, cap)
        return cols[0], cols[1], valid_r, ovf.reshape(1)

    fn = jax.jit(shard_map_compat(stage, mesh, P(DP_AXIS), P(DP_AXIS)))
    k, v, ok, p = shard_rows(mesh, jnp.asarray(keys), jnp.asarray(vals),
                             jnp.asarray(valid), jnp.asarray(pid))
    rk, rv, rvalid, ovf = fn(k, v, ok, p)
    rk, rv, rvalid, ovf = map(np.asarray, (rk, rv, rvalid, ovf))
    assert ovf.sum() == 0

    # multiset of (key, val) pairs survives the exchange exactly
    sent = sorted(zip(keys[valid], vals[valid]))
    got = sorted(zip(rk[rvalid], rv[rvalid]))
    assert len(sent) == len(got)
    assert np.allclose([a for a, _ in sent], [a for a, _ in got])
    assert np.allclose([b for _, b in sent], [b for _, b in got])

    # routing: device d received exactly the rows with pid == d
    per_dev = NDEV * cap
    for d in range(NDEV):
        lo, hi = d * per_dev, (d + 1) * per_dev
        dk = rk[lo:hi][rvalid[lo:hi]]
        assert (dk % NDEV == d).all()


def test_all_to_all_rows_overflow_detected(mesh):
    rows_per_dev = 128
    n = NDEV * rows_per_dev
    keys = np.zeros(n, dtype=np.int64)  # everything to device 0
    valid = np.ones(n, dtype=bool)
    pid = np.zeros(n, dtype=np.int32)
    cap = 16  # far under rows_per_dev

    def stage(k, ok, p):
        cols, valid_r, ovf = all_to_all_rows([k], ok, p, DP_AXIS,
                                             NDEV, cap)
        return cols[0], valid_r, ovf.reshape(1)

    fn = jax.jit(shard_map_compat(stage, mesh, P(DP_AXIS), P(DP_AXIS)))
    k, ok, p = shard_rows(mesh, jnp.asarray(keys),
                          jnp.asarray(valid), jnp.asarray(pid))
    rk, rvalid, ovf = fn(k, ok, p)
    ovf = np.asarray(ovf)
    rvalid = np.asarray(rvalid)
    assert ovf.sum() == n - NDEV * cap  # dropped rows all reported
    assert rvalid.sum() == NDEV * cap   # survivors all delivered


@pytest.mark.parametrize("descending", [False, True])
def test_distributed_sort_global_order(mesh, descending):
    rng = np.random.default_rng(11)
    rows_per_dev = 1024
    n = NDEV * rows_per_dev
    keys = rng.integers(-10_000, 10_000, n).astype(np.int64)
    payload = rng.random(n)
    valid = rng.random(n) < 0.9
    cap = 2 * rows_per_dev

    fn = distributed_sort(mesh, num_payloads=1, capacity=cap,
                          descending=descending)
    k, ok, pay = shard_rows(mesh, jnp.asarray(keys), jnp.asarray(valid),
                            jnp.asarray(payload))
    out_k, out_v, out_p, ovf = fn(k, ok, pay)
    out_k, out_v, out_p, ovf = map(np.asarray, (out_k, out_v, out_p, ovf))
    assert ovf.sum() == 0

    # multiset preserved, payload rides with its key
    want = np.sort(keys[valid])
    got_all = out_k[out_v]
    assert np.array_equal(np.sort(got_all), want)
    pair_want = sorted(zip(keys[valid], payload[valid]))
    pair_got = sorted(zip(out_k[out_v], out_p[out_v]))
    assert np.allclose([b for _, b in pair_want],
                       [b for _, b in pair_got])

    # per-device locally sorted; device boundaries globally ordered
    per_dev = NDEV * cap
    prev_extreme = None
    for d in range(NDEV):
        seg = out_k[d * per_dev:(d + 1) * per_dev]
        sv = out_v[d * per_dev:(d + 1) * per_dev]
        dk = seg[sv]
        if len(dk) == 0:
            continue
        step = np.diff(dk)
        assert (step <= 0).all() if descending else (step >= 0).all()
        if prev_extreme is not None:
            if descending:
                assert prev_extreme >= dk.max()
            else:
                assert prev_extreme <= dk.min()
        prev_extreme = dk.min() if descending else dk.max()


def test_distributed_hash_join_matches_oracle(mesh):
    rng = np.random.default_rng(23)
    rows_per_dev = 512
    n = NDEV * rows_per_dev
    # duplicate keys on both sides + nulls: the full inner-join matrix
    bkeys = rng.integers(0, 300, n).astype(np.int64)
    bvals = rng.random(n)
    bvalid = rng.random(n) < 0.9
    pkeys = rng.integers(0, 300, n).astype(np.int64)
    pvals = rng.random(n)
    pvalid = rng.random(n) < 0.9

    cap = 4 * rows_per_dev
    pair_cap = 1 << 17

    fn = distributed_hash_join(mesh, num_build_payloads=1,
                               num_probe_payloads=1, capacity=cap,
                               pair_cap=pair_cap)
    args = shard_rows(mesh, jnp.asarray(bkeys), jnp.asarray(bvalid),
                      jnp.asarray(bvals), jnp.asarray(pkeys),
                      jnp.asarray(pvalid), jnp.asarray(pvals))
    jk, jv, jb, jp, counts = fn(*args)
    jk, jv, jb, jp, counts = map(np.asarray, (jk, jv, jb, jp, counts))
    counts = counts.reshape(NDEV, 3)
    assert counts[:, 1].sum() == 0 and counts[:, 2].sum() == 0, \
        "exchange overflowed"

    # numpy oracle: every (build, probe) pair with equal valid keys
    import collections
    build_by_key = collections.defaultdict(list)
    for k, v, ok in zip(bkeys, bvals, bvalid):
        if ok:
            build_by_key[k].append(v)
    want = []
    for k, v, ok in zip(pkeys, pvals, pvalid):
        if ok:
            for bv in build_by_key.get(k, ()):
                want.append((k, round(bv, 9), round(v, 9)))
    got = [(k, round(b, 9), round(p, 9))
           for k, b, p in zip(jk[jv], jb[jv], jp[jv])]
    assert sorted(got) == sorted(want)
    assert counts[:, 0].sum() == len(want)


def test_distributed_join_then_sort_pipeline(mesh):
    """Join output feeds the distributed sort — the two-exchange pipeline
    dryrun_multichip validates at scale (VERDICT r4 #4)."""
    rng = np.random.default_rng(31)
    rows_per_dev = 256
    n = NDEV * rows_per_dev
    bkeys = rng.integers(0, 64, n).astype(np.int64)
    bvals = rng.random(n)
    pkeys = rng.integers(0, 64, n).astype(np.int64)
    pvals = rng.random(n)
    ones = np.ones(n, dtype=bool)

    cap = 4 * rows_per_dev
    pair_cap = 1 << 16
    jfn = distributed_hash_join(mesh, 1, 1, cap, pair_cap)
    args = shard_rows(mesh, jnp.asarray(bkeys), jnp.asarray(ones),
                      jnp.asarray(bvals), jnp.asarray(pkeys),
                      jnp.asarray(ones), jnp.asarray(pvals))
    jk, jv, jb, jp, counts = jfn(*args)

    sfn = distributed_sort(mesh, num_payloads=2, capacity=pair_cap,
                           samples_per_device=64)
    out = sfn(jk, jv, jb, jp)
    out_k, out_v = np.asarray(out[0]), np.asarray(out[1])
    assert np.asarray(out[-1]).sum() == 0
    # valid rows, concatenated in device order, are globally sorted and
    # carry the same multiset the join emitted
    got = out_k[out_v]
    want = np.sort(np.asarray(jk)[np.asarray(jv)])
    assert np.array_equal(np.sort(got), want)
    assert (np.diff(got) >= 0).all()


def test_distributed_sort_int64_min_descending(mesh):
    """Descending integer order must not negate (INT64_MIN wraps)."""
    rows_per_dev = 64
    n = NDEV * rows_per_dev
    rng = np.random.default_rng(41)
    keys = rng.integers(-100, 100, n).astype(np.int64)
    keys[0] = np.iinfo(np.int64).min
    keys[1] = np.iinfo(np.int64).max
    ones = np.ones(n, dtype=bool)
    fn = distributed_sort(mesh, num_payloads=0, capacity=n,
                          descending=True)
    out_k, out_v, ovf = fn(*shard_rows(mesh, jnp.asarray(keys),
                                       jnp.asarray(ones)))
    assert np.asarray(ovf).sum() == 0
    got = np.asarray(out_k)[np.asarray(out_v)]
    assert got[0] == np.iinfo(np.int64).max
    assert got[-1] == np.iinfo(np.int64).min
    assert (np.diff(got) <= 0).all()


@pytest.mark.parametrize("descending", [False, True])
def test_distributed_sort_float_nan_is_largest(mesh, descending):
    """Spark NaN ordering: NaN is the largest value — last on ASC,
    first on DESC — and never corrupts the range bounds."""
    rows_per_dev = 128
    n = NDEV * rows_per_dev
    rng = np.random.default_rng(43)
    keys = rng.normal(size=n) * 100
    nan_at = rng.choice(n, size=17, replace=False)
    keys[nan_at] = np.nan
    valid = rng.random(n) < 0.95
    fn = distributed_sort(mesh, num_payloads=0, capacity=n,
                          descending=descending)
    out_k, out_v, ovf = fn(*shard_rows(mesh, jnp.asarray(keys),
                                       jnp.asarray(valid)))
    assert np.asarray(ovf).sum() == 0
    got = np.asarray(out_k)[np.asarray(out_v)]
    n_nan = int(np.isnan(keys[valid]).sum())
    assert int(np.isnan(got).sum()) == n_nan
    finite = got[~np.isnan(got)]
    if descending:
        assert np.isnan(got[:n_nan]).all()   # NaN first
        assert (np.diff(finite) <= 0).all()
    else:
        assert np.isnan(got[-n_nan:]).all()  # NaN last
        assert (np.diff(finite) >= 0).all()


def test_distributed_hash_join_nan_keys_never_match(mesh):
    """NaN float keys are nulls to the exchange primitive (callers
    canonicalize for Spark's NaN == NaN); padding must never surface."""
    rows_per_dev = 64
    n = NDEV * rows_per_dev
    rng = np.random.default_rng(47)
    bkeys = rng.integers(0, 40, n).astype(np.float64)
    bkeys[::7] = np.nan
    bvals = rng.random(n)
    pkeys = rng.integers(0, 40, n).astype(np.float64)
    pkeys[::5] = np.nan
    pvals = rng.random(n)
    ones = np.ones(n, dtype=bool)
    fn = distributed_hash_join(mesh, 1, 1, capacity=4 * rows_per_dev,
                               pair_cap=1 << 15)
    jk, jv, jb, jp, counts = fn(*shard_rows(
        mesh, jnp.asarray(bkeys), jnp.asarray(ones), jnp.asarray(bvals),
        jnp.asarray(pkeys), jnp.asarray(ones), jnp.asarray(pvals)))
    counts = np.asarray(counts).reshape(NDEV, 3)
    assert counts[:, 1:].sum() == 0
    import collections
    bb = collections.defaultdict(int)
    for k in bkeys[~np.isnan(bkeys)]:
        bb[k] += 1
    want = sum(bb.get(k, 0) for k in pkeys[~np.isnan(pkeys)])
    got_k = np.asarray(jk)[np.asarray(jv)]
    assert len(got_k) == want == counts[:, 0].sum()
    assert not np.isnan(got_k).any()
