"""Kernel-layer unit tests (pure-native tier, SURVEY.md §4 tier 1):
selection/compaction, order-key sort, segmented reduce, cast, bloom, strings
validated against numpy / pyarrow / python references.
"""

import numpy as np
import jax.numpy as jnp
import pyarrow as pa
import pytest

from blaze_tpu.kernels import selection, compare, sort as ksort, cast as kcast
from blaze_tpu.kernels import bloom, strings, hashing
from blaze_tpu import schema as S


def test_compaction_indices_stable():
    rng = np.random.default_rng(0)
    mask = rng.random(512) < 0.3
    idx, count = selection.compaction_indices(jnp.asarray(mask))
    idx, count = np.asarray(idx), int(count)
    assert count == mask.sum()
    np.testing.assert_array_equal(idx[:count], np.nonzero(mask)[0])


def test_take_null_propagation():
    data = jnp.arange(10, dtype=jnp.int64)
    valid = jnp.asarray([True] * 9 + [False])
    idx = jnp.asarray([0, 9, -1, 12, 3])
    g, v = selection.take(data, valid, idx)
    np.testing.assert_array_equal(np.asarray(v), [True, False, False, False, True])
    assert int(g[0]) == 0 and int(g[4]) == 3


def test_partition_offsets():
    pids = jnp.asarray([2, 0, 1, 2, 0, 1, 1], dtype=jnp.int32)
    mask = jnp.asarray([1, 1, 1, 1, 1, 1, 0], dtype=bool)
    counts, offsets = selection.partition_start_offsets(pids, mask, 3)
    np.testing.assert_array_equal(np.asarray(counts), [2, 2, 2])
    np.testing.assert_array_equal(np.asarray(offsets), [0, 2, 4, 6])


@pytest.mark.parametrize("descending", [False, True])
@pytest.mark.parametrize("nulls_first", [False, True])
def test_order_key_int_matches_python(descending, nulls_first):
    rng = np.random.default_rng(1)
    vals = rng.integers(-1000, 1000, 200).astype(np.int64)
    valid = rng.random(200) < 0.9
    bucket, key = compare.order_key(jnp.asarray(vals), jnp.asarray(valid),
                                    S.INT64, descending, nulls_first)
    perm = np.asarray(compare.lexsort_indices([bucket, key]))
    got = [(None if not valid[i] else int(vals[i])) for i in perm]

    def py_key(i):
        null_rank = 0 if nulls_first else 2
        if not valid[i]:
            return (null_rank, 0)
        return (1, -vals[i] if descending else vals[i])
    expect_perm = sorted(range(200), key=py_key)
    expect = [(None if not valid[i] else int(vals[i])) for i in expect_perm]
    assert got == expect


def test_order_key_float_nan_sorts_last():
    vals = np.array([1.5, np.nan, -np.inf, np.inf, -0.0, 0.0, -2.5])
    bucket, key = compare.order_key(jnp.asarray(vals), None, S.FLOAT64, False, True)
    perm = np.asarray(compare.lexsort_indices([bucket, key]))
    ordered = vals[perm]
    assert np.isneginf(ordered[0]) and ordered[1] == -2.5
    assert np.isposinf(ordered[-2]) and np.isnan(ordered[-1])


def test_lexsort_multi_key_matches_numpy():
    rng = np.random.default_rng(2)
    a = rng.integers(0, 5, 300).astype(np.int64)
    b = rng.integers(-50, 50, 300).astype(np.int64)
    keys = compare.order_keys(
        [(jnp.asarray(a), None, S.INT64), (jnp.asarray(b), None, S.INT64)],
        [False, True], [True, True])
    perm = np.asarray(compare.lexsort_indices(list(keys)))
    expect = np.lexsort((-b, a))  # last key primary in np.lexsort
    np.testing.assert_array_equal(a[perm], a[expect])
    np.testing.assert_array_equal(b[perm], b[expect])


def test_group_ids_and_segment_sum():
    keys = jnp.asarray([1, 1, 2, 2, 2, 5, 7, 7], dtype=jnp.int64)
    valid = jnp.asarray([True] * 8)
    gids, ngroups = ksort.group_ids_from_sorted([keys], valid)
    assert int(ngroups) == 4
    vals = jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0])
    sums = ksort.segment_sum(vals, gids, 8)
    np.testing.assert_allclose(np.asarray(sums)[:4], [3.0, 12.0, 6.0, 15.0])


def test_cast_float_to_int_spark_semantics():
    vals = jnp.asarray([1.9, -1.9, np.nan, np.inf, -np.inf, 2**40 * 1.0])
    out, v = kcast.cast_column(vals, None, S.FLOAT64, S.INT32)
    np.testing.assert_array_equal(
        np.asarray(out), [1, -1, 0, 2**31 - 1, -(2**31), 2**31 - 1])


def test_cast_int_wraparound():
    vals = jnp.asarray([300, -300, 127], dtype=jnp.int64)
    out, _ = kcast.cast_column(vals, None, S.INT64, S.INT8)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.array([300, -300, 127]).astype(np.int8))


def test_cast_to_decimal_half_up_and_overflow():
    vals = jnp.asarray([1.25, -1.25, 1.24, 99999.0])
    out, v = kcast.cast_column(vals, None, S.FLOAT64, S.decimal(5, 2))
    np.testing.assert_array_equal(np.asarray(out)[:3], [125, -125, 124])
    assert not bool(np.asarray(v)[3])  # 99999.00 needs p=7 > 5 -> null


def test_decimal_rescale_half_up():
    vals = jnp.asarray([125, -125, 114, -114], dtype=jnp.int64)  # scale 2
    out, _ = kcast.cast_column(vals, None, S.decimal(5, 2), S.decimal(5, 1))
    np.testing.assert_array_equal(np.asarray(out), [13, -13, 11, -11])


def test_bloom_filter_roundtrip_and_probe():
    items = np.arange(0, 1000, 3, dtype=np.int64)
    f = bloom.SparkBloomFilter(bloom.optimal_num_bits(len(items), 0.01),
                               bloom.optimal_num_hashes(
                                   len(items), bloom.optimal_num_bits(len(items), 0.01)))
    f.put_longs(items)
    probe = jnp.asarray(np.arange(1000, dtype=np.int64))
    hits = np.asarray(f.might_contain_longs(probe))
    assert hits[items].all()  # no false negatives
    fp_rate = hits[np.setdiff1d(np.arange(1000), items)].mean()
    assert fp_rate < 0.05
    # serde roundtrip
    g = bloom.SparkBloomFilter.from_bytes(f.to_bytes())
    np.testing.assert_array_equal(g.words, f.words)
    assert g.num_hashes == f.num_hashes


def test_string_predicates():
    arr = pa.array(["hello", "help", "yelp", None, "lo", ""])
    (mat, lens), valid = hashing.string_column_to_padded_bytes(arr)
    mat, lens = jnp.asarray(mat), jnp.asarray(lens)
    np.testing.assert_array_equal(
        np.asarray(strings.starts_with(mat, lens, b"hel"))[:3], [True, True, False])
    np.testing.assert_array_equal(
        np.asarray(strings.ends_with(mat, lens, b"lp"))[:3], [False, True, True])
    np.testing.assert_array_equal(
        np.asarray(strings.contains(mat, lens, b"el")),
        [True, True, True, False, False, False])
    np.testing.assert_array_equal(
        np.asarray(strings.eq_const(mat, lens, b"lo")),
        [False, False, False, False, True, False])


def test_string_utf8_length_and_case():
    arr = pa.array(["abc", "héllo", "", "ABC"])
    (mat, lens), _ = hashing.string_column_to_padded_bytes(arr)
    mat, lens = jnp.asarray(mat), jnp.asarray(lens)
    np.testing.assert_array_equal(
        np.asarray(strings.length_utf8_chars(mat, lens)), [3, 5, 0, 3])
    up = np.asarray(strings.upper_ascii(mat))
    assert bytes(up[0][:3]) == b"ABC"


def test_substring_fixed():
    arr = pa.array(["hello world", "hi", ""])
    (mat, lens), _ = hashing.string_column_to_padded_bytes(arr)
    out, out_len = strings.substring_fixed(jnp.asarray(mat), jnp.asarray(lens), 7, 5)
    assert bytes(np.asarray(out)[0][:int(out_len[0])]) == b"world"
    assert int(out_len[1]) == 0 or bytes(np.asarray(out)[1][:int(out_len[1])]) == b""


# -- regression tests from code review ---------------------------------------

def test_cast_float_to_int64_range_2_62_to_2_63():
    vals = jnp.asarray([5.0e18, -5.0e18, 9.3e18, -9.3e18])
    out, _ = kcast.cast_column(vals, None, S.FLOAT64, S.INT64)
    out = np.asarray(out)
    assert out[0] == 5000000000000000000 and out[1] == -5000000000000000000
    assert out[2] == 2**63 - 1 and out[3] == -(2**63)


def test_cast_int_to_decimal_no_wraparound():
    vals = jnp.asarray([1844674407370955162, 5], dtype=jnp.int64)
    out, v = kcast.cast_column(vals, None, S.INT64, S.decimal(18, 1))
    assert not bool(np.asarray(v)[0])  # overflow -> null, not wrapped value
    assert bool(np.asarray(v)[1]) and int(np.asarray(out)[1]) == 50


def test_decimal_upscale_no_wraparound():
    vals = jnp.asarray([10**17, 3], dtype=jnp.int64)  # scale 0 -> scale 2
    out, v = kcast.cast_column(vals, None, S.decimal(18, 0), S.decimal(18, 2))
    assert not bool(np.asarray(v)[0])
    assert int(np.asarray(out)[1]) == 300


def test_wide_decimal_stays_host_side():
    import decimal as pydec
    from blaze_tpu.batch import ColumnBatch, HostColumn
    arr = pa.array([pydec.Decimal(2**63), None], type=pa.decimal128(38, 0))
    cb = ColumnBatch.from_arrow(pa.table({"d": arr}))
    assert isinstance(cb.columns[0], HostColumn)
    assert cb.to_arrow().column(0)[0].as_py() == pydec.Decimal(2**63)


def test_timestamp_ms_normalized_to_us():
    from blaze_tpu.batch import ColumnBatch
    arr = pa.array([1000], type=pa.timestamp("ms"))
    cb = ColumnBatch.from_arrow(pa.table({"t": arr}))
    assert int(np.asarray(cb.columns[0].data)[0]) == 1_000_000


def test_substring_start_zero_is_one():
    arr = pa.array(["abc"])
    (mat, lens), _ = hashing.string_column_to_padded_bytes(arr)
    out, out_len = strings.substring_fixed(jnp.asarray(mat), jnp.asarray(lens), 0, 2)
    assert bytes(np.asarray(out)[0][:int(out_len[0])]) == b"ab"


def test_segment_first_takes_first_row_even_if_null():
    vals = jnp.asarray([10, 20, 30], dtype=jnp.int64)
    valid = jnp.asarray([False, True, True])
    gids = jnp.asarray([0, 0, 1])
    v, ok = ksort.segment_first(vals, valid, gids, 3)
    assert not bool(np.asarray(ok)[0])          # first row of group 0 is null
    assert int(np.asarray(v)[1]) == 30 and bool(np.asarray(ok)[1])
    assert not bool(np.asarray(ok)[2])          # empty segment


def test_padded_bytes_vectorized_matches_pylist():
    arr = pa.array(["", None, "abcd", "xy", None, "a" * 40])
    (mat, lens), valid = hashing.string_column_to_padded_bytes(arr)
    assert lens.tolist() == [0, 0, 4, 2, 0, 40]
    assert valid.tolist() == [True, False, True, True, False, True]
    assert bytes(mat[2][:4]) == b"abcd" and bytes(mat[5][:40]) == b"a" * 40
    sliced = arr.slice(2, 3)  # non-zero offset path
    (m2, l2), v2 = hashing.string_column_to_padded_bytes(sliced)
    assert l2.tolist() == [4, 2, 0] and bytes(m2[0][:4]) == b"abcd"


# -- regression tests from code review ---------------------------------------

def test_padded_bytes_all_empty_or_null():
    import pyarrow as pa
    from blaze_tpu.kernels.hashing import string_column_to_padded_bytes
    for arr in (pa.array(["", "", ""]), pa.array([None, None], type=pa.utf8())):
        (mat, lengths), valid = string_column_to_padded_bytes(arr)
        assert mat.shape[0] == len(arr)
        assert (lengths == 0).all()


def test_ns_timestamp_ingest_truncates():
    import pyarrow as pa
    from blaze_tpu.batch import ColumnBatch
    cb = ColumnBatch.from_arrow(
        pa.table({"t": pa.array([1001, 2999], type=pa.timestamp("ns"))}))
    assert np.asarray(cb.columns[0].data)[:2].tolist() == [1, 2]


def test_cast_int_seconds_to_timestamp():
    from blaze_tpu.kernels.cast import cast_column
    from blaze_tpu import schema as S
    data, v = cast_column(jnp.asarray([5, -3], dtype=jnp.int64), None,
                          S.INT64, S.TIMESTAMP_MICROS)
    assert np.asarray(data).tolist() == [5_000_000, -3_000_000]
    back, _ = cast_column(data, None, S.TIMESTAMP_MICROS, S.INT64)
    assert np.asarray(back).tolist() == [5, -3]
    # floor division for negative sub-second timestamps
    back2, _ = cast_column(jnp.asarray([-1500000], dtype=jnp.int64), None,
                           S.TIMESTAMP_MICROS, S.INT64)
    assert np.asarray(back2).tolist() == [-2]


def test_cast_decimal_to_long_exact():
    from blaze_tpu.kernels.cast import cast_column
    from blaze_tpu import schema as S
    big = 999999999999999999  # > 2^53: float64 path would round this
    data, v = cast_column(jnp.asarray([big, -big], dtype=jnp.int64), None,
                          S.decimal(18, 0), S.INT64)
    assert np.asarray(data).tolist() == [big, -big]
    # scale>0 truncates toward zero
    data2, _ = cast_column(jnp.asarray([1999, -1999], dtype=jnp.int64), None,
                           S.decimal(10, 3), S.INT64)
    assert np.asarray(data2).tolist() == [1, -1]
    # overflow -> null
    data3, v3 = cast_column(jnp.asarray([12345678901], dtype=jnp.int64), None,
                            S.decimal(18, 0), S.INT32)
    assert not bool(np.asarray(v3)[0])


def test_substring_negative_start_past_front():
    from blaze_tpu.kernels.strings import substring_fixed
    from blaze_tpu.kernels.hashing import string_column_to_padded_bytes
    import pyarrow as pa
    (mat, lengths), _ = string_column_to_padded_bytes(pa.array(["abc", "hello"]))
    out, out_len = substring_fixed(jnp.asarray(mat), jnp.asarray(lengths), -5, 4)
    # Spark substring('abc', -5, 4) = 'ab'; substring('hello', -5, 4) = 'hell'
    got = [bytes(np.asarray(out)[i][:int(out_len[i])]).decode()
           for i in range(2)]
    assert got == ["ab", "hell"]
