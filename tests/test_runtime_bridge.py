"""Task runtime + native bridge tests (ref rt.rs / exec.rs behaviors)."""

import ctypes
import json
import io
import os

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import schema as S
from blaze_tpu.bridge.resource import put_resource
from blaze_tpu.bridge.runtime import NativeExecutionRuntime, execute_plan
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import schema_to_dict


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _task_def(plan, partition=0):
    return {"stage_id": 1, "partition_id": partition, "num_partitions": 1,
            "plan": plan}


def _scan_ir(rid, t):
    return {"kind": "memory_scan", "resource_id": rid,
            "schema": schema_to_dict(S.Schema.from_arrow(t.schema))}


def test_runtime_produces_batches():
    t = pa.table({"a": pa.array(range(1000))})
    put_resource("rt1", t)
    ir = {"kind": "filter",
          "predicates": [{"kind": "binary", "op": ">",
                          "l": {"kind": "column", "index": 0},
                          "r": {"kind": "literal", "value": 500,
                                "type": {"id": "int64"}}}],
          "input": _scan_ir("rt1", t)}
    rt = NativeExecutionRuntime(_task_def(ir)).start()
    try:
        total = sum(rb.num_rows for rb in rt.batches())
        assert total == 499
    finally:
        metrics = rt.finalize()
        assert metrics.to_dict()["name"]


def test_runtime_error_propagates():
    ir = {"kind": "memory_scan", "resource_id": "does-not-exist",
          "schema": {"fields": []}}
    with pytest.raises(KeyError):
        NativeExecutionRuntime(_task_def(ir))


def test_runtime_error_from_producer_thread():
    t = pa.table({"s": pa.array(["a", "b"])})
    put_resource("rt2", t)
    # cast string->struct is unsupported -> error must surface via next_batch
    ir = {"kind": "project", "names": ["x"],
          "exprs": [{"kind": "scalar_function", "name": "no_such_fn",
                     "args": [{"kind": "column", "index": 0}]}],
          "input": _scan_ir("rt2", t)}
    rt = NativeExecutionRuntime(_task_def(ir)).start()
    try:
        with pytest.raises(KeyError):
            for _ in rt.batches():
                pass
    finally:
        rt.finalize()


def test_execute_plan_json_task_definition():
    t = pa.table({"a": pa.array([3, 1, 2])})
    put_resource("rt3", t)
    ir = {"kind": "sort", "specs": [{"expr": {"kind": "column", "index": 0}}],
          "input": _scan_ir("rt3", t)}
    batches = execute_plan(json.dumps(_task_def(ir)))
    got = pa.Table.from_batches(batches)
    assert got.column("a").to_pylist() == [1, 2, 3]


def test_native_codec_roundtrip():
    from blaze_tpu.bridge.native import get_codec
    codec = get_codec()
    if codec is None:
        pytest.skip("native codec not built")
    payload = b"hello blaze " * 1000
    frame = codec.compress_frame(payload)
    assert frame[0] == 1  # CODEC_ZSTD
    import struct
    clen = struct.unpack_from("<I", frame, 1)[0]
    assert len(frame) == clen + 5
    back = codec.decompress(frame[5:])
    assert back == payload


def test_native_codec_in_ipc_path():
    """Framed IPC written with the native codec reads back identically."""
    from blaze_tpu.shuffle.ipc import (IpcCompressionReader,
                                       IpcCompressionWriter)
    sink = io.BytesIO()
    w = IpcCompressionWriter(sink)
    rb = pa.record_batch({"x": pa.array(range(500))})
    w.write_batch(rb)
    w.finish()
    sink.seek(0)
    out = list(IpcCompressionReader(sink).read_batches())
    assert pa.Table.from_batches(out).equals(pa.Table.from_batches([rb]))


def test_host_bridge_c_abi_end_to_end():
    """Drive the C entry points (callNative/nextBatch/finalizeNative) the
    way a host engine would — through the shared library's C ABI."""
    from blaze_tpu.bridge.native import get_host_bridge
    lib = get_host_bridge()
    if lib is None:
        pytest.skip("host bridge not built")
    t = pa.table({"a": pa.array(range(100)),
                  "s": pa.array([f"r{i}" for i in range(100)])})
    put_resource("hb1", t)
    ir = {"kind": "limit", "limit": 7, "input": _scan_ir("hb1", t)}
    err = ctypes.c_char_p()
    handle = lib.blaze_call_native(
        json.dumps(_task_def(ir)).encode(), ctypes.byref(err))
    assert handle > 0, err.value
    rows = 0
    while True:
        buf = ctypes.POINTER(ctypes.c_uint8)()
        n = lib.blaze_next_batch(handle, ctypes.byref(buf), ctypes.byref(err))
        assert n >= 0, err.value
        if n == 0:
            break
        data = ctypes.string_at(buf, n)
        lib.blaze_free_buffer(buf)
        with pa.ipc.open_stream(io.BytesIO(data)) as r:
            for rb in r:
                rows += rb.num_rows
    assert rows == 7
    metrics = ctypes.c_char_p()
    rc = lib.blaze_finalize_native(handle, ctypes.byref(metrics),
                                   ctypes.byref(err))
    assert rc == 0
    md = json.loads(metrics.value.decode())
    assert "name" in md


def test_host_bridge_c_data_ffi_roundtrip():
    """Zero-copy Arrow C-Data handoff through the .so (VERDICT r4 #5):
    blaze_next_batch_ffi exports each batch into caller structs; pyarrow
    imports them back; contents must match the IPC path bit-for-bit."""
    from blaze_tpu.bridge.native import get_host_bridge
    lib = get_host_bridge()
    if lib is None:
        pytest.skip("host bridge lib unavailable")
    t = pa.table({"a": pa.array(range(257)),
                  "b": pa.array([float(i) / 7 for i in range(257)])})
    put_resource("ffi_rt", t)
    ir = {"kind": "filter",
          "predicates": [{"kind": "binary", "op": ">",
                          "l": {"kind": "column", "index": 0},
                          "r": {"kind": "literal", "value": 56,
                                "type": {"id": "int64"}}}],
          "input": _scan_ir("ffi_rt", t)}
    err = ctypes.c_char_p()
    handle = lib.blaze_call_native(
        json.dumps(_task_def(ir)).encode(), ctypes.byref(err))
    assert handle, err.value

    class _ArrowArray(ctypes.Structure):
        _fields_ = [("length", ctypes.c_int64),
                    ("null_count", ctypes.c_int64),
                    ("offset", ctypes.c_int64),
                    ("n_buffers", ctypes.c_int64),
                    ("n_children", ctypes.c_int64),
                    ("buffers", ctypes.c_void_p),
                    ("children", ctypes.c_void_p),
                    ("dictionary", ctypes.c_void_p),
                    ("release", ctypes.c_void_p),
                    ("private_data", ctypes.c_void_p)]

    class _ArrowSchema(ctypes.Structure):
        _fields_ = [("format", ctypes.c_char_p),
                    ("name", ctypes.c_char_p),
                    ("metadata", ctypes.c_void_p),
                    ("flags", ctypes.c_int64),
                    ("n_children", ctypes.c_int64),
                    ("children", ctypes.c_void_p),
                    ("dictionary", ctypes.c_void_p),
                    ("release", ctypes.c_void_p),
                    ("private_data", ctypes.c_void_p)]

    got = []
    while True:
        arr = _ArrowArray()
        sch = _ArrowSchema()
        r = lib.blaze_next_batch_ffi(handle, ctypes.byref(arr),
                                     ctypes.byref(sch), ctypes.byref(err))
        assert r >= 0, err.value
        if r == 0:
            break
        rb = pa.RecordBatch._import_from_c(ctypes.addressof(arr),
                                           ctypes.addressof(sch))
        got.append(rb)
    metrics = ctypes.c_char_p()
    assert lib.blaze_finalize_native(handle, ctypes.byref(metrics),
                                     ctypes.byref(err)) == 0
    out = pa.Table.from_batches(got)
    want = t.filter(pa.compute.greater(t["a"], 56))
    assert out.num_rows == want.num_rows == 200
    assert out.column("a").to_pylist() == want.column("a").to_pylist()
    assert out.column("b").to_pylist() == want.column("b").to_pylist()


def test_host_bridge_ffi_import_batch():
    """Host -> engine C-Data import feeding an ffi_reader plan."""
    from blaze_tpu.bridge.native import get_host_bridge
    lib = get_host_bridge()
    if lib is None:
        pytest.skip("host bridge lib unavailable")
    rb = pa.record_batch({"x": pa.array([1, 2, 3], pa.int64())})
    # export from pyarrow, hand the struct addresses through the C ABI
    from pyarrow.cffi import ffi as _f  # structs via pyarrow's own cffi
    arr = _f.new("struct ArrowArray*")
    sch = _f.new("struct ArrowSchema*")
    rb._export_to_c(int(_f.cast("uintptr_t", arr)),
                    int(_f.cast("uintptr_t", sch)))
    err = ctypes.c_char_p()
    rows = lib.blaze_ffi_import_batch(
        b"ffi-import-test", ctypes.c_void_p(int(_f.cast("uintptr_t", arr))),
        ctypes.c_void_p(int(_f.cast("uintptr_t", sch))), ctypes.byref(err))
    assert rows == 3, err.value
    from blaze_tpu.bridge.resource import get_resource
    batches = get_resource("ffi-import-test")
    assert batches and batches[0].column(0).to_pylist() == [1, 2, 3]


def test_jni_bridge_symbols_and_layout():
    """The JNI shim must export the reference's four natives
    (JniBridge.java:49-55) and link against the host bridge."""
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    so = os.path.join(root, "native", "build", "libblaze_jni_bridge.so")
    if not os.path.exists(so):
        pytest.skip("jni shim not built")
    out = subprocess.run(["nm", "-D", so], capture_output=True,
                         text=True).stdout
    for sym in ("Java_org_apache_auron_jni_JniBridge_callNative",
                "Java_org_apache_auron_jni_JniBridge_nextBatch",
                "Java_org_apache_auron_jni_JniBridge_finalizeNative",
                "Java_org_apache_auron_jni_JniBridge_onExit"):
        assert sym in out, sym


@pytest.mark.parametrize("force_ipc", [False, True])
def test_bridge_pull_batch_prefers_ffi_falls_back_to_ipc(force_ipc):
    """bridge_pull_batch is the has_cdata_ffi consumer: C-Data when the
    .so exports it, IPC bytes otherwise — same batches either way."""
    from blaze_tpu.bridge.native import bridge_pull_batch, get_host_bridge
    lib = get_host_bridge()
    if lib is None:
        pytest.skip("host bridge lib unavailable")
    t = pa.table({"a": pa.array(range(100)),
                  "s": pa.array([f"r{i}" for i in range(100)])})
    put_resource("pull1", t)
    ir = _scan_ir("pull1", t)
    err = ctypes.c_char_p()
    handle = lib.blaze_call_native(
        json.dumps(_task_def(ir)).encode(), ctypes.byref(err))
    assert handle, err.value
    saved = lib.has_cdata_ffi
    if force_ipc:
        lib.has_cdata_ffi = False  # stale-.so policy
    try:
        got = []
        while True:
            rb = bridge_pull_batch(lib, handle)
            if rb is None:
                break
            got.append(rb)
    finally:
        lib.has_cdata_ffi = saved
        metrics = ctypes.c_char_p()
        lib.blaze_finalize_native(handle, ctypes.byref(metrics),
                                  ctypes.byref(err))
    out = pa.Table.from_batches(got)
    assert out.column("a").to_pylist() == list(range(100))
    assert out.column("s").to_pylist() == [f"r{i}" for i in range(100)]
