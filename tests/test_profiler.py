"""End-to-end query profiler: standardized operator metrics, span
tracing, XLA compile accounting, and EXPLAIN ANALYZE.

Covers the acceptance query shape (ParquetScan -> Filter -> Project ->
HashAggregate with a hash-partition shuffle) through explain_analyze on
the staged wire path, the per-partition MetricNode merge (child names
must survive merging), the tracer, and meter_jit compile/cache-hit
classification.
"""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.bridge import tracing, xla_stats
from blaze_tpu.bridge.metrics import BASELINE_METRICS, MetricNode
from blaze_tpu.memory import MemManager


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


# -- MetricNode merge (the multi-partition tree merge) -----------------------

def _tree(rows, ns, mem):
    root = MetricNode(name="AggExec")
    root.add("output_rows", rows)
    root.add("elapsed_compute_ns", ns)
    root.set_max("mem_used", mem)
    child = root.child(0, name="ScanExec")
    child.add("output_rows", rows * 2)
    return root


def test_merge_preserves_child_names_and_sums():
    merged = MetricNode()
    merged.merge_from(_tree(10, 100, 5))
    merged.merge_from(_tree(7, 50, 9))
    assert merged.name == "AggExec"
    # regression: merging into a bare skeleton used to drop child names
    assert merged.children[0].name == "ScanExec"
    assert merged.get("output_rows") == 17
    assert merged.get("elapsed_compute_ns") == 150
    assert merged.children[0].get("output_rows") == 34
    # mem_used is a peak: max across partitions, never a sum
    assert merged.get("mem_used") == 9


def test_merge_across_real_multi_partition_execution():
    from blaze_tpu.ops import FilterExec, MemoryScanExec
    from blaze_tpu.exprs import BinaryExpr, col, lit

    t = pa.table({"a": pa.array(range(300), type=pa.int64())})
    scan = MemoryScanExec.from_arrow(t, 3)  # 3 partitions
    plan = FilterExec(scan, [BinaryExpr("<", col(0), lit(150))])

    merged = MetricNode()
    for p in range(plan.num_partitions):
        before = plan.collect_metrics()
        for _ in plan.execute(p):
            pass
        merged.merge_from(plan.collect_metrics().diff(before))
    assert merged.name == "FilterExec"
    assert merged.children[0].name == "MemoryScanExec"
    assert merged.get("output_rows") == 150
    assert merged.children[0].get("output_rows") == 300
    assert merged.get("elapsed_compute_ns") > 0


def test_snapshot_diff_roundtrip():
    a = _tree(10, 100, 5)
    snap = a.snapshot()
    a.add("output_rows", 3)
    a.children[0].add("output_rows", 1)
    d = a.diff(snap)
    assert d.get("output_rows") == 3
    assert d.children[0].get("output_rows") == 1
    assert d.get("elapsed_compute_ns") == 0
    rt = MetricNode.from_dict(a.to_dict())
    assert rt.to_dict() == a.to_dict()


# -- tracing -----------------------------------------------------------------

def test_tracer_spans_context_and_jsonl(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tracing.start_tracing(path)
    try:
        with tracing.execution_context(query="q-test", stage=1):
            with tracing.execution_context(partition=2):
                with tracing.span("task", mode="sync"):
                    pass
            tracing.instant("xla_compile", kernel="k1", ns=12)
    finally:
        spans = tracing.stop_tracing()
    assert [s["name"] for s in spans] == ["task", "xla_compile"]
    task = spans[0]
    assert task["ctx"] == {"query": "q-test", "stage": 1, "partition": 2}
    assert task["attrs"] == {"mode": "sync"}
    assert task["dur_ns"] >= 0
    # the instant sees the outer frames only (partition frame popped)
    assert spans[1]["ctx"] == {"query": "q-test", "stage": 1}
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert [s["name"] for s in lines] == ["task", "xla_compile"]


def test_tracing_disabled_is_noop():
    assert not tracing.enabled()
    before = len(tracing.spans())
    with tracing.span("never"):
        pass
    tracing.emit_span("never", 123)
    assert len(tracing.spans()) == before


def test_operator_spans_emitted_from_task_runtime():
    from blaze_tpu.bridge.runtime import execute_plan
    from blaze_tpu.ops import FilterExec, MemoryScanExec
    from blaze_tpu.exprs import BinaryExpr, col, lit

    t = pa.table({"a": pa.array(range(64), type=pa.int64())})
    plan = FilterExec(MemoryScanExec.from_arrow(t, 1),
                      [BinaryExpr("<", col(0), lit(32))])
    tracing.start_tracing()
    try:
        execute_plan(plan)
    finally:
        spans = tracing.stop_tracing()
    names = {s["name"] for s in spans}
    assert "task" in names
    assert any(n.startswith("operator:") for n in names)
    task = next(s for s in spans if s["name"] == "task")
    assert task["ctx"]["partition"] == 0


# -- XLA compile accounting --------------------------------------------------

def test_meter_jit_classifies_compiles_and_cache_hits():
    import jax.numpy as jnp

    xla_stats.reset()
    f = xla_stats.meter_jit(lambda x: x * 2 + 1, name="test.kernel")
    a = jnp.arange(8)
    f(a)          # compile
    f(a)          # cache hit
    f(a + 1)      # same shape: cache hit
    f(jnp.arange(16))  # new shape: compile
    rep = xla_stats.compile_report()
    e = rep["kernels"]["test.kernel"]
    assert e["calls"] == 4
    assert e["compiles"] == 2
    assert e["cache_hits"] == 2
    assert e["compile_ns"] > 0
    assert e["distinct_signatures"] == 2
    assert not e["shape_churn"]
    assert rep["totals"]["compiles"] == 2


def test_meter_jit_flags_shape_churn():
    import jax.numpy as jnp

    xla_stats.reset()
    f = xla_stats.meter_jit(lambda x: x.sum(), name="churny")
    for n in range(1, xla_stats.SHAPE_CHURN_THRESHOLD + 2):
        f(jnp.arange(n))
    e = xla_stats.compile_report()["kernels"]["churny"]
    assert e["shape_churn"]
    assert e["compiles"] == xla_stats.SHAPE_CHURN_THRESHOLD + 1


def test_meter_jit_emits_compile_instants():
    import jax.numpy as jnp

    xla_stats.reset()
    f = xla_stats.meter_jit(lambda x: x + 1, name="traced.kernel")
    tracing.start_tracing()
    try:
        f(jnp.arange(4))   # compile -> instant
        f(jnp.arange(4))   # cache hit -> nothing
    finally:
        spans = tracing.stop_tracing()
    compiles = [s for s in spans if s["name"] == "xla_compile"]
    assert len(compiles) == 1
    assert compiles[0]["attrs"]["kernel"] == "traced.kernel"


def test_transfer_accounting_from_batch_layer():
    from blaze_tpu.bridge.placement import host_resident
    if host_resident():
        pytest.skip("H2D accounting requires device placement")
    from blaze_tpu.batch import ColumnBatch
    before = xla_stats.snapshot()
    cb = ColumnBatch.from_arrow(pa.RecordBatch.from_arrays(
        [pa.array(np.arange(1024, dtype=np.int64))], names=["a"]))
    cb.to_arrow()
    d = xla_stats.delta(before)
    assert d["h2d_bytes"] > 0


# -- explain_analyze ---------------------------------------------------------

def test_explain_analyze_in_process_plan():
    from blaze_tpu.ops import FilterExec, MemoryScanExec, ProjectExec
    from blaze_tpu.exprs import BinaryExpr, col, lit
    from blaze_tpu.plan import explain_analyze

    t = pa.table({"a": pa.array(range(100), type=pa.int64()),
                  "b": pa.array(np.linspace(0, 1, 100))})
    scan = MemoryScanExec.from_arrow(t, batch_rows=32)
    flt = FilterExec(scan, [BinaryExpr("<", col(0), lit(50))])
    plan = ProjectExec(flt, [col(0)], ["a"])

    prof = explain_analyze(plan, keep_result=True)
    assert prof.output_rows == 50
    assert prof.result.num_rows == 50
    text = prof.render_text()
    # the planner collapses Filter->Project into one FilterProjectExec
    for op in ("FilterProjectExec", "MemoryScanExec"):
        assert op in text
    assert "XLA:" in text and "transfers:" in text

    def every_node(n):
        yield n
        for c in n.children:
            yield from every_node(c)

    for node in every_node(prof.tree):
        assert node.values.get("output_rows", 0) > 0, node.name
        assert node.values.get("elapsed_compute_ns", 0) > 0, node.name


@pytest.fixture
def staged_mode():
    from blaze_tpu import config
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


def _acceptance_plan(tmp_path):
    """ParquetScan -> Filter -> Project -> partial HashAgg ->
    hash-partition shuffle -> final HashAgg (the TPC-DS q01 inner
    shape)."""
    rng = np.random.default_rng(11)
    n = 20_000
    t = pa.table({"k": pa.array(rng.integers(0, 200, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"in-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    plan = {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": 3},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {
                    "kind": "project",
                    "exprs": [{"kind": "column", "index": 0},
                              {"kind": "column", "index": 1}],
                    "names": ["k", "v"],
                    "input": {
                        "kind": "filter",
                        "predicates": [
                            {"kind": "binary", "op": ">=",
                             "l": {"kind": "column", "name": "k"},
                             "r": {"kind": "literal", "value": 10,
                                   "type": {"id": "int64"}}}],
                        "input": {"kind": "parquet_scan",
                                  "schema": schema,
                                  "file_groups": [[paths[0]],
                                                  [paths[1]]]}}}}}}
    return plan, t


def test_explain_analyze_staged_acceptance(tmp_path, staged_mode):
    from blaze_tpu.bridge import profiling
    from blaze_tpu.plan import explain_analyze

    plan, t = _acceptance_plan(tmp_path)
    prof = explain_analyze(plan, work_dir=str(tmp_path / "dag"),
                           query_id="accept-q01", keep_result=True)
    assert prof.exec_mode == "staged"
    assert prof.partitions == 3

    # the shuffle split is stitched back: the full operator chain shows
    # in ONE tree, scan at the leaf
    text = prof.render_text()
    # Filter->Project arrives collapsed to one FilterProjectExec node
    for op in ("IpcReaderExec", "ShuffleWriterExec", "FilterProjectExec",
               "ParquetScanExec"):
        assert op in text, text

    def every_node(n):
        yield n
        for c in n.children:
            yield from every_node(c)

    nodes = list(every_node(prof.tree))
    assert len(nodes) >= 6
    for node in nodes:
        assert node.values.get("output_rows", 0) > 0, (node.name, text)
        assert node.values.get("elapsed_compute_ns", 0) > 0, node.name

    # XLA accounting is part of the profile (zero on the host-vectorized
    # path, but the keys must be reported)
    assert "total_compiles" in prof.xla
    assert "total_cache_hits" in prof.xla
    assert "XLA: compiles=" in text

    # result rode along and matches the oracle
    import pandas as pd
    want = (t.to_pandas().query("k >= 10").groupby("k", as_index=False)
            .v.sum().rename(columns={"v": "s"}))
    got = prof.result.to_pandas().sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(
        got, want.sort_values("k").reset_index(drop=True),
        check_exact=False)

    # the same profile is registered for the HTTP service
    stored = profiling.get_profile("accept-q01")
    assert stored is not None
    assert stored["tree"]["values"]["output_rows"] > 0
    assert stored["output_rows"] == prof.output_rows


def test_dag_scheduler_collects_stage_metrics(tmp_path, staged_mode):
    from blaze_tpu.plan.stages import DagScheduler

    plan, _t = _acceptance_plan(tmp_path)
    sched = DagScheduler(work_dir=str(tmp_path / "dag"))
    sched.run_collect(plan)
    # one tree per stage, merged across that stage's tasks
    assert set(sched.stage_metrics) == {0, 1}
    map_tree = sched.stage_metrics[0]
    assert map_tree.name == "ShuffleWriterExec"
    assert map_tree.get("output_rows") > 0
    result_tree = sched.collect_metrics()
    assert result_tree is sched.stage_metrics[1]
    assert result_tree.get("output_rows") > 0
