"""Streaming runtime: epochs, watermarks, checkpoints, exactly-once.

Covers the continuous micro-batch executor (streaming/executor.py) end
to end — bounded Kafka source -> event-time tumbling window -> parquet
sink through DagScheduler — plus the unit seams: window assignment,
watermark tracking, late-side policies, first-wins checkpoint commits,
serving-layer cancellation/deadline/memory-quota, and the flink
micro-batch operator's per-partition offset contract.
"""

import json
import os
import time

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.memory import MemManager
from blaze_tpu.ops.kafka import KafkaRecord
from blaze_tpu.ops.window import (EventTimeWindowSpec, EventTimeWindowState,
                                  WatermarkTracker)
from blaze_tpu.serving.context import (DeadlineExceeded, QueryCancelled,
                                       QueryContext, QueryMemoryExceeded)
from blaze_tpu.streaming import (CheckpointManager, ExactlyOnceParquetSink,
                                 MemoryStreamSource, StreamExecutor,
                                 StreamWindowConfig,
                                 streaming_service_executor)

pytestmark = pytest.mark.stream


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


SCHEMA = {"fields": [
    {"name": "k", "type": {"id": "utf8"}, "nullable": True},
    {"name": "v", "type": {"id": "int64"}, "nullable": True}]}


def _plan(num_partitions=1, operator_id="stream-test"):
    return {"kind": "kafka_scan", "topic": "orders", "format": "json",
            "operator_id": operator_id, "num_partitions": num_partitions,
            "schema": SCHEMA}


def _records(partition, n, ts0=0, ts_step=100, key="k0", vals=None):
    """Monotone-timestamp records for one partition (no late arrivals)."""
    out = []
    for i in range(n):
        row = {"k": key if isinstance(key, str) else key(i),
               "v": (vals[i] if vals else i)}
        out.append(KafkaRecord(value=json.dumps(row).encode("utf-8"),
                               offset=i, partition=partition,
                               timestamp_ms=ts0 + i * ts_step))
    return out


def _window_oracle(partitions, window_ms):
    """Pure-python recompute: (k, window_start) -> [sum_v, count]."""
    acc = {}
    for recs in partitions:
        for r in recs:
            row = json.loads(r.value)
            ws = r.timestamp_ms - r.timestamp_ms % window_ms
            slot = acc.setdefault((row["k"], ws), [0, 0])
            slot[0] += row["v"]
            slot[1] += 1
    return sorted((k, ws, ws + window_ms, s, c)
                  for (k, ws), (s, c) in acc.items())


def _sink_rows(sink):
    t = sink.committed_table()
    return sorted(zip(t.column("k").to_pylist(),
                      t.column("window_start").to_pylist(),
                      t.column("window_end").to_pylist(),
                      t.column("sum_v").to_pylist(),
                      t.column("count").to_pylist()))


WIN = StreamWindowConfig(spec=EventTimeWindowSpec(size_ms=1000),
                         keys=["k"], aggs=[("sum", "v"), ("count", None)])


# -- unit seams ---------------------------------------------------------

def test_event_time_window_spec_assign():
    tumble = EventTimeWindowSpec(size_ms=1000)
    assert tumble.assign(0) == [0]
    assert tumble.assign(999) == [0]
    assert tumble.assign(1000) == [1000]
    assert tumble.end(1000) == 2000
    slide = EventTimeWindowSpec(size_ms=1000, slide_ms=250)
    # Flink semantics: every window [s, s+size) with s = ts - (ts % slide)
    # stepping back while s > ts - size
    assert slide.assign(1000) == [1000, 750, 500, 250]
    assert slide.assign(100) == [0, -250, -500, -750]
    assert slide.end(250) == 1250


def test_watermark_tracker_semantics():
    tr = WatermarkTracker(lateness_ms=10)
    assert tr.watermark() is None  # nothing observed yet
    tr.observe(0, 500)
    tr.observe(1, 1000)
    assert tr.watermark() == 490  # min over partitions minus lateness
    tr.observe(0, 2000)
    assert tr.watermark() == 990  # now bounded by partition 1
    # monotone: a late-appearing slow partition cannot pull the clock
    # back (the watermark only moves forward)
    tr.observe(2, 100)
    assert tr.watermark() == 990
    snap = tr.snapshot()
    tr2 = WatermarkTracker(lateness_ms=10)
    tr2.restore(snap)
    assert tr2.watermark() == tr.watermark()
    # observing older timestamps after restore never regresses either
    tr2.observe(0, 100)
    assert tr2.watermark() >= 990


def _state(policy, spec=None):
    schema = pa.schema([("k", pa.string()), ("v", pa.int64()),
                       ("__event_time", pa.int64())])
    return EventTimeWindowState(spec or EventTimeWindowSpec(size_ms=1000),
                                schema, "__event_time", ["k"],
                                [("sum", "v"), ("count", None)],
                                late_policy=policy), schema


def _rb(schema, rows):
    return pa.RecordBatch.from_arrays(
        [pa.array([r[0] for r in rows], pa.string()),
         pa.array([r[1] for r in rows], pa.int64()),
         pa.array([r[2] for r in rows], pa.int64())], schema=schema)


def test_late_policy_drop():
    st, schema = _state("drop")
    try:
        late = st.add_batch(_rb(schema, [("a", 1, 100), ("a", 2, 50)]),
                            watermark=99)
        assert late == 1 and st.late_records == 1
        t = st.flush()
        assert t.column("sum_v").to_pylist() == [1]  # late row dropped
        assert st.take_late() == []
    finally:
        st.close()


def test_late_policy_side():
    st, schema = _state("side")
    try:
        st.add_batch(_rb(schema, [("a", 1, 100), ("b", 2, 50)]),
                     watermark=99)
        side = st.take_late()
        assert [r["k"] for r in side] == ["b"]  # routed, not folded
        assert st.flush().column("sum_v").to_pylist() == [1]
    finally:
        st.close()


def test_late_policy_accept_refires_pane():
    st, schema = _state("accept")
    try:
        st.add_batch(_rb(schema, [("a", 1, 100)]), watermark=None)
        first = st.advance(2000)  # pane [0, 1000) fires
        assert first.column("sum_v").to_pylist() == [1]
        st.add_batch(_rb(schema, [("a", 5, 200)]), watermark=2000)
        # the accepted late row re-opens the pane with its fired
        # accumulators: the re-fire is a corrected CUMULATIVE pane
        refire = st.flush()
        assert refire.column("sum_v").to_pylist() == [6]
        assert refire.column("count").to_pylist() == [2]
    finally:
        st.close()


def test_late_policy_accept_survives_checkpoint_roundtrip():
    """Fired accumulators ride the snapshot so a recovered query still
    re-fires cumulative panes for accepted late rows."""
    import json as _json

    st, schema = _state("accept")
    try:
        st.add_batch(_rb(schema, [("a", 1, 100), ("a", 3, 150)]),
                     watermark=None)
        st.advance(2000)  # pane fires with sum=4, count=2
        snap = _json.loads(_json.dumps(st.snapshot()))
    finally:
        st.close()

    st2, _ = _state("accept")
    try:
        st2.restore(snap)
        st2.add_batch(_rb(schema, [("a", 10, 200)]), watermark=2000)
        refire = st2.flush()
        assert refire.column("sum_v").to_pylist() == [14]
        assert refire.column("count").to_pylist() == [3]
    finally:
        st2.close()


def test_windows_fire_only_after_watermark():
    st, schema = _state("drop")
    try:
        st.add_batch(_rb(schema, [("a", 1, 100), ("a", 2, 1100)]))
        assert st.advance(999).num_rows == 0  # wm < end of [0, 1000)
        fired = st.advance(1000)
        assert fired.column("window_start").to_pylist() == [0]
        assert st.flush().column("window_start").to_pylist() == [1000]
    finally:
        st.close()


def test_checkpoint_commit_first_wins(tmp_path):
    ck = CheckpointManager(str(tmp_path))
    assert ck.commit(0, {"offsets": {"0": 5}, "x": "winner"})
    assert not ck.commit(0, {"offsets": {"0": 9}, "x": "loser"})
    assert ck.load(0)["x"] == "winner"  # first manifest is the truth
    assert ck.committed(0) and not ck.committed(1)
    assert ck.commit(1, {"offsets": {"0": 7}})
    assert ck.epochs() == [0, 1]
    epoch, manifest = ck.latest()
    assert epoch == 1
    assert CheckpointManager.offsets_from(manifest) == {0: 7}


def test_sink_all_empty_epochs_returns_empty_table(tmp_path):
    """Committed-but-empty epochs are a legitimate state (windows that
    produced no output): committed_table() must return an empty table
    with the sink schema, not claim nothing committed."""
    sink = ExactlyOnceParquetSink(str(tmp_path / "sink"))
    schema = pa.schema([("k", pa.string()), ("sum_v", pa.int64())])
    empty = pa.Table.from_arrays(
        [pa.array([], pa.string()), pa.array([], pa.int64())],
        schema=schema)
    for e in (0, 1):
        assert sink.promote(e, sink.write_attempt(e, empty))
    t = sink.committed_table()
    assert t.num_rows == 0 and t.schema.equals(schema)
    # raising stays reserved for NO committed epoch at all
    with pytest.raises(FileNotFoundError, match="no committed"):
        ExactlyOnceParquetSink(str(tmp_path / "fresh")).committed_table()


def test_executor_prefers_source_partition_count(tmp_path):
    """A multi-partition source must not be shadowed down to the scan's
    default of 1 (which would silently poll only partition 0 and
    declare end-of-stream with the rest unread)."""
    parts = [_records(0, 4), _records(1, 4, key="k1")]
    ex = StreamExecutor(_plan(1), MemoryStreamSource(parts), WIN,
                        sink_dir=str(tmp_path / "sink"),
                        checkpoint_dir=str(tmp_path / "ckpt"))
    summary = ex.run()
    assert summary["records_consumed"] == 8  # BOTH partitions read
    assert _sink_rows(ex.sink) == _window_oracle(parts, 1000)

    # an explicit override that disagrees with the source is an error,
    # not a silent drop
    with pytest.raises(ValueError, match="disagrees"):
        StreamExecutor(_plan(2), MemoryStreamSource(parts[:1]), WIN,
                       sink_dir=str(tmp_path / "s2"), num_partitions=2)


# -- the continuous query -----------------------------------------------

def test_stream_executor_happy_path(tmp_path):
    parts = [_records(0, 30, ts0=0, key=lambda i: f"k{i % 3}"),
             _records(1, 30, ts0=50, key=lambda i: f"k{i % 2}")]
    ex = StreamExecutor(_plan(2), MemoryStreamSource(parts), WIN,
                        sink_dir=str(tmp_path / "sink"),
                        checkpoint_dir=str(tmp_path / "ckpt"),
                        max_records_per_poll=8)
    summary = ex.run()
    assert summary["epochs"] > 1  # a real multi-epoch run
    assert summary["records_consumed"] == 60
    assert summary["recoveries"] == 0
    assert _sink_rows(ex.sink) == _window_oracle(parts, 1000)

    # epoch-boundary contract: every NON-final epoch only emitted panes
    # the manifest's own watermark had already passed
    ck = CheckpointManager(str(tmp_path / "ckpt"))
    for e in ck.epochs():
        m = ck.load(e)
        if m.get("final"):
            continue
        path = os.path.join(str(tmp_path / "sink"),
                            f"epoch-{e:06d}.parquet")
        t = pq.read_table(path)
        if t.num_rows:
            wm = m["watermark"]["wm"]
            assert max(t.column("window_end").to_pylist()) <= wm


def test_chaos_recovery_exactly_once(tmp_path):
    parts = [_records(0, 40, key=lambda i: f"k{i % 4}"),
             _records(1, 40, ts0=30, key=lambda i: f"k{i % 3}")]

    base = StreamExecutor(_plan(2), MemoryStreamSource(parts), WIN,
                          sink_dir=str(tmp_path / "base-sink"),
                          checkpoint_dir=str(tmp_path / "base-ckpt"),
                          max_records_per_poll=5)
    base.run()

    xla_stats.reset()
    chaos = StreamExecutor(_plan(2), MemoryStreamSource(parts), WIN,
                           sink_dir=str(tmp_path / "chaos-sink"),
                           checkpoint_dir=str(tmp_path / "chaos-ckpt"),
                           max_records_per_poll=5)
    with faults.scoped(("stream-epoch", dict(at=(3,))),
                       ("checkpoint-commit", dict(at=(5,))),
                       seed=11):
        summary = chaos.run()
        injected = sum(st["fires"] for st in faults.stats().values())
    assert injected == 2
    assert summary["recoveries"] == 2
    # replay after both faults is invisible in the sink: bit-identical
    # output, zero lost, zero duplicated rows
    assert _sink_rows(chaos.sink) == _sink_rows(base.sink)
    st = xla_stats.stream_stats()
    assert st["stream_recoveries"] == 2
    assert st["stream_checkpoints"] == summary["epochs"]


def test_recovery_budget_exhaustion_reraises(tmp_path):
    parts = [_records(0, 20)]
    ex = StreamExecutor(_plan(1), MemoryStreamSource(parts), WIN,
                        sink_dir=str(tmp_path / "sink"),
                        checkpoint_dir=str(tmp_path / "ckpt"),
                        max_records_per_poll=4)
    with config.scoped(**{config.STREAM_MAX_RECOVERIES.key: 1}):
        # `at` is the occurrence index: evals 2 and 3 are epoch 1 and
        # its replay — one recovery allowed, second fault re-raises
        with faults.scoped(("stream-epoch", dict(at=(2, 3))),
                           seed=3):
            with pytest.raises(faults.InjectedFault):
                ex.run()


def test_stream_through_query_service(tmp_path):
    from blaze_tpu.serving.service import QueryService
    parts = [_records(0, 24, key=lambda i: f"k{i % 3}")]
    holder = {}

    def build(plan_ir, ctx):
        ex = StreamExecutor(plan_ir, MemoryStreamSource(parts), WIN,
                            sink_dir=str(tmp_path / "sink"),
                            checkpoint_dir=str(tmp_path / "ckpt"),
                            ctx=ctx, max_records_per_poll=6)
        holder["ex"] = ex
        return ex

    service = QueryService(max_concurrent=1,
                           executor=streaming_service_executor(build))
    try:
        summary = service.submit(_plan(1), tenant="t").result(timeout=120)
        assert summary["epochs"] >= 4
        assert _sink_rows(holder["ex"].sink) == _window_oracle([parts[0]],
                                                               1000)
    finally:
        service.shutdown()


def test_serving_deadline_tears_down_epoch(tmp_path):
    from blaze_tpu.serving.service import QueryService
    parts = [_records(0, 2000, key=lambda i: f"k{i % 5}")]

    def build(plan_ir, ctx):
        return StreamExecutor(plan_ir, MemoryStreamSource(parts), WIN,
                              sink_dir=str(tmp_path / "sink"),
                              checkpoint_dir=str(tmp_path / "ckpt"),
                              ctx=ctx, max_records_per_poll=2)

    service = QueryService(max_concurrent=1,
                           executor=streaming_service_executor(build))
    try:
        handle = service.submit(_plan(1), tenant="t", deadline_ms=1)
        with pytest.raises(DeadlineExceeded):
            handle.result(timeout=120)
    finally:
        service.shutdown()


def test_serving_cancel_stops_stream(tmp_path):
    from blaze_tpu.serving.service import QueryService
    parts = [_records(0, 4000, key=lambda i: f"k{i % 5}")]
    holder = {}

    def build(plan_ir, ctx):
        ex = StreamExecutor(plan_ir, MemoryStreamSource(parts), WIN,
                            sink_dir=str(tmp_path / "sink"),
                            checkpoint_dir=str(tmp_path / "ckpt"),
                            ctx=ctx, max_records_per_poll=4)
        holder["ex"] = ex
        return ex

    service = QueryService(max_concurrent=1,
                           executor=streaming_service_executor(build))
    try:
        handle = service.submit(_plan(1), tenant="t")
        deadline = time.monotonic() + 60
        while (holder.get("ex") is None
               or holder["ex"].epochs_committed < 1):
            if time.monotonic() > deadline:
                pytest.fail("stream never committed an epoch")
            time.sleep(0.01)
        assert handle.cancel()
        with pytest.raises(QueryCancelled):
            handle.result(timeout=120)
        # cancellation landed at an epoch boundary, long before drain
        assert holder["ex"].epochs_committed < 1000
    finally:
        service.shutdown()


def test_mem_quota_on_window_state_kills_query(tmp_path):
    # every record opens a new (window, key) accumulator and the window
    # never fires (no watermark passes its end), so state grows until
    # the per-query quota breaches climb the degrade ladder to kill
    parts = [_records(0, 40, ts_step=10, key=lambda i: f"u{i}")]
    win = StreamWindowConfig(spec=EventTimeWindowSpec(size_ms=10 ** 9),
                             keys=["k"], aggs=[("sum", "v")])
    ctx = QueryContext("q-mem", mem_quota=600)
    ex = StreamExecutor(_plan(1), MemoryStreamSource(parts), win,
                        sink_dir=str(tmp_path / "sink"),
                        checkpoint_dir=str(tmp_path / "ckpt"),
                        ctx=ctx, max_records_per_poll=2)
    with pytest.raises(QueryMemoryExceeded):
        ex.run()
    assert ctx.degrade_level >= 3


# -- observability ------------------------------------------------------

def test_stream_counters_prometheus_and_explain(tmp_path):
    xla_stats.reset()
    parts = [_records(0, 12, key=lambda i: f"k{i % 2}")]
    ex = StreamExecutor(_plan(1), MemoryStreamSource(parts), WIN,
                        sink_dir=str(tmp_path / "sink"),
                        checkpoint_dir=str(tmp_path / "ckpt"),
                        max_records_per_poll=4)
    ex.run()
    snap = xla_stats.snapshot()
    assert snap["stream_epochs"] >= 1
    assert snap["stream_sink_commits"] == snap["stream_epochs"]

    from blaze_tpu.bridge.profiling import prometheus_text
    prom = prometheus_text()
    assert "blaze_stream_epochs_total" in prom
    assert "blaze_stream_window_state_bytes" in prom  # gauge, no _total
    assert "blaze_stream_window_state_bytes_last" not in prom

    # the explain-analyze footer renders the stream line from the same
    # counters a profile wrapping this query would capture as its delta
    from blaze_tpu.plan.explain import explain_analyze
    profile = explain_analyze(
        {"kind": "kafka_scan", "topic": "t", "format": "json",
         "schema": SCHEMA,
         "mock_data_json_array": json.dumps([{"k": "a", "v": 1}])},
        record=False)
    profile.xla.update({k: v for k, v in snap.items()
                        if k.startswith("stream_")})
    text = profile.render_text()
    assert "stream: epochs=" in text and "dup_skips=" in text


# -- flink micro-batch operator satellites ------------------------------

def _flink_plan():
    return {
        "flinkVersion": "1.18",
        "nodes": [
            {"id": 1, "type": "stream-exec-table-source-scan_1",
             "scanTableSource": {"table": {"resolvedTable": {
                 "schema": {"columns": [
                     {"name": "user_id", "dataType": "BIGINT"},
                     {"name": "amount", "dataType": "DOUBLE"}]},
                 "options": {"connector": "kafka", "topic": "orders",
                             "format": "json"}}}}},
            {"id": 2, "type": "stream-exec-calc_2",
             "projection": [
                 {"kind": "INPUT_REF", "inputIndex": 0, "type": "BIGINT"},
                 {"kind": "INPUT_REF", "inputIndex": 1,
                  "type": "DOUBLE"}],
             "condition": None},
            {"id": 3, "type": "stream-exec-sink_3"}],
        "edges": [{"source": 1, "target": 2},
                  {"source": 2, "target": 3}],
    }


def _flink_recs(partition, n):
    return [KafkaRecord(value=json.dumps(
        {"user_id": partition * 100 + i, "amount": float(i)}).encode(),
        offset=i, partition=partition) for i in range(n)]


def test_flink_per_partition_offsets_on_midbatch_failure(monkeypatch):
    from blaze_tpu.bridge import runtime as bridge_runtime
    from blaze_tpu.convert.flink_runtime import FlinkMicroBatchOperator

    real = bridge_runtime.NativeExecutionRuntime
    calls = {"n": 0}

    class FlakySecondTask:
        def __init__(self, td):
            calls["n"] += 1
            self._boom = calls["n"] == 2
            self._inner = real(td)

        def start(self):
            self._inner.start()
            return self

        def batches(self):
            if self._boom:
                raise RuntimeError("injected: partition 1 task died")
            return self._inner.batches()

        def finalize(self):
            self._inner.finalize()

    monkeypatch.setattr(bridge_runtime, "NativeExecutionRuntime",
                        FlakySecondTask)
    op = FlinkMicroBatchOperator(_flink_plan(), num_partitions=2)
    p0, p1 = _flink_recs(0, 3), _flink_recs(1, 3)
    delivered = []
    with pytest.raises(RuntimeError, match="partition 1"):
        for _part, batches in op.iter_micro_batch([p0, p1]):
            delivered.extend(batches)
    # partition 0's output was HANDED OVER before the failure, so its
    # offset committed; partition 1 stays rewindable
    assert sorted(i for rb in delivered
                  for i in rb.column(0).to_pylist()) == [0, 1, 2]
    assert op.offsets == {0: 3, 1: 0}

    # replay feeds only the un-committed partition
    replay = [[r for r in p0 if r.offset >= op.offsets[0]],
              [r for r in p1 if r.offset >= op.offsets[1]]]
    out = op.run_micro_batch(replay)
    ids = sorted(i for rb in out
                 for i in rb.column(0).to_pylist())
    assert ids == [100, 101, 102]  # p1 rows exactly once, p0 not re-run
    assert op.offsets == {0: 3, 1: 3}


def test_flink_midbatch_failure_rewinds_whole_batch(monkeypatch):
    """run_micro_batch hands output back only at return, so a mid-batch
    failure must NOT leave earlier partitions' offsets committed — their
    batches died with the exception and a replay has to re-emit them
    (at-least-once, zero loss)."""
    from blaze_tpu.bridge import runtime as bridge_runtime
    from blaze_tpu.convert.flink_runtime import FlinkMicroBatchOperator

    real = bridge_runtime.NativeExecutionRuntime
    calls = {"n": 0}

    class FlakySecondTask:
        def __init__(self, td):
            calls["n"] += 1
            self._boom = calls["n"] == 2
            self._inner = real(td)

        def start(self):
            self._inner.start()
            return self

        def batches(self):
            if self._boom:
                raise RuntimeError("injected: partition 1 task died")
            return self._inner.batches()

        def finalize(self):
            self._inner.finalize()

    monkeypatch.setattr(bridge_runtime, "NativeExecutionRuntime",
                        FlakySecondTask)
    op = FlinkMicroBatchOperator(_flink_plan(), num_partitions=2)
    p0, p1 = _flink_recs(0, 3), _flink_recs(1, 3)
    with pytest.raises(RuntimeError, match="partition 1"):
        op.run_micro_batch([p0, p1])
    # nothing was delivered, so nothing may be marked consumed
    assert op.offsets == {0: 0, 1: 0}

    out = op.run_micro_batch([p0, p1])  # full replay
    ids = sorted(i for rb in out for i in rb.column(0).to_pylist())
    assert ids == [0, 1, 2, 100, 101, 102]  # every row exactly once
    assert op.offsets == {0: 3, 1: 3}


def test_flink_idempotent_replay_under_checkpoint(tmp_path):
    from blaze_tpu.convert.flink_runtime import FlinkMicroBatchOperator
    ck = CheckpointManager(str(tmp_path))
    recs = [_flink_recs(0, 4)]

    op = FlinkMicroBatchOperator(_flink_plan(), num_partitions=1,
                                 checkpoint=ck)
    out = op.run_micro_batch(recs, epoch=0)
    assert sum(rb.num_rows for rb in out) == 4
    assert op.offsets == {0: 4}
    assert ck.committed(0)

    # a recovering driver blindly re-feeds epoch 0 into a FRESH operator:
    # the committed manifest short-circuits the run and restores offsets
    op2 = FlinkMicroBatchOperator(_flink_plan(), num_partitions=1,
                                  checkpoint=ck)
    assert op2.run_micro_batch(recs, epoch=0) == []
    assert op2.offsets == {0: 4}
    assert op2.batches_run == 0  # nothing executed on replay
