"""Broadcast nested-loop join tests vs pandas cross-merge oracles
(the auron.enable.bnlj operator for non-equi joins)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from blaze_tpu.exprs import BinaryExpr, col
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.joins import JoinType
from blaze_tpu.ops.joins.bnlj import BroadcastNestedLoopJoinExec


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _tables(seed=0, nl=400, nr=60):
    rng = np.random.default_rng(seed)
    left = pa.table({"a": pa.array(rng.integers(0, 100, nl),
                                   type=pa.int64()),
                     "b": pa.array(rng.random(nl))})
    right = pa.table({"lo": pa.array(rng.integers(0, 80, nr),
                                     type=pa.int64()),
                      "hi": pa.array(rng.integers(20, 100, nr),
                                     type=pa.int64())})
    return left, right


def _run(plan):
    out = [b.compact().to_arrow() for b in plan.execute(0)]
    out = [b for b in out if b.num_rows]
    return (pa.Table.from_batches(out).to_pandas() if out
            else pd.DataFrame())


def _oracle_pairs(left, right):
    l = left.to_pandas().reset_index(names="li")
    r = right.to_pandas().reset_index(names="ri")
    x = l.merge(r, how="cross")
    return x[(x.a >= x.lo) & (x.a <= x.hi)]


def _band_filter():
    # a between lo and hi, on the joined (left+right) schema
    return BinaryExpr("and",
                      BinaryExpr(">=", col(0), col(2)),
                      BinaryExpr("<=", col(0), col(3)))


@pytest.mark.parametrize("jt,expected", [
    (JoinType.INNER, "pairs"),
    (JoinType.LEFT, "left_rows"),
    (JoinType.LEFT_SEMI, "semi"),
    (JoinType.LEFT_ANTI, "anti"),
    (JoinType.EXISTENCE, "existence"),
    (JoinType.FULL, "full"),
])
def test_band_join(jt, expected):
    left, right = _tables()
    plan = BroadcastNestedLoopJoinExec(
        MemoryScanExec.from_arrow(left, batch_rows=64),
        MemoryScanExec.from_arrow(right),
        jt, build_side="right", join_filter=_band_filter())
    got = _run(plan)
    pairs = _oracle_pairs(left, right)
    matched_left = set(pairs.li)
    matched_right = set(pairs.ri)
    nl, nr = left.num_rows, right.num_rows
    if expected == "pairs":
        assert len(got) == len(pairs)
    elif expected == "left_rows":
        assert len(got) == len(pairs) + (nl - len(matched_left))
    elif expected == "semi":
        assert len(got) == len(matched_left)
    elif expected == "anti":
        assert len(got) == nl - len(matched_left)
    elif expected == "existence":
        assert len(got) == nl
        assert int(got["exists"].sum()) == len(matched_left)
    elif expected == "full":
        assert len(got) == (len(pairs) + (nl - len(matched_left)) +
                            (nr - len(matched_right)))


def test_cross_join_no_condition():
    left, right = _tables(nl=30, nr=7)
    plan = BroadcastNestedLoopJoinExec(
        MemoryScanExec.from_arrow(left), MemoryScanExec.from_arrow(right),
        JoinType.INNER)
    got = _run(plan)
    assert len(got) == 30 * 7


def test_empty_build_side():
    left, _ = _tables(nl=10)
    empty = pa.table({"lo": pa.array([], type=pa.int64()),
                      "hi": pa.array([], type=pa.int64())})
    plan = BroadcastNestedLoopJoinExec(
        MemoryScanExec.from_arrow(left), MemoryScanExec.from_arrow(empty),
        JoinType.LEFT, build_side="right", join_filter=_band_filter())
    got = _run(plan)
    assert len(got) == 10
    assert got["lo"].isna().all()


def test_converter_maps_bnlj(tmp_path):
    import pyarrow.parquet as pq
    from blaze_tpu.convert import convert_spark_plan
    from blaze_tpu.plan import create_plan
    import tests.test_convert_spark as C

    left, right = _tables(nl=50, nr=10)
    pl = str(tmp_path / "l.parquet")
    pr = str(tmp_path / "r.parquet")
    pq.write_table(left, pl)
    pq.write_table(right, pr)
    a, b = C.attr("a", "long", 1), C.attr("b", "double", 2)
    lo, hi = C.attr("lo", "long", 3), C.attr("hi", "long", 4)
    cond = C.binexpr("And",
                     C.binexpr("GreaterThanOrEqual", C.attr("a", "long", 1),
                               C.attr("lo", "long", 3)),
                     C.binexpr("LessThanOrEqual", C.attr("a", "long", 1),
                               C.attr("hi", "long", 4)))
    join = C.plan_node(
        "joins.BroadcastNestedLoopJoinExec",
        {"joinType": "Inner", "buildSide": "BuildRight",
         "condition": cond},
        [C.scan_node([a[0], b[0]], [[pl]]),
         C.plan_node("exchange.BroadcastExchangeExec", {},
                     [C.scan_node([lo[0], hi[0]], [[pr]])])])
    res = convert_spark_plan(join)
    plan = create_plan(res.plan)
    got = _run(plan)
    assert len(got) == len(_oracle_pairs(left, right))


def test_full_join_multi_partition_probe():
    """Unmatched build rows must be emitted exactly ONCE across probe
    partitions (matched state is shared; the last partition emits)."""
    left, right = _tables(seed=5, nl=600, nr=40)
    plan = BroadcastNestedLoopJoinExec(
        MemoryScanExec.from_arrow(left, num_partitions=3, batch_rows=64),
        MemoryScanExec.from_arrow(right),
        JoinType.FULL, build_side="right", join_filter=_band_filter())
    out = []
    for p in range(plan.num_partitions):
        out.extend(b.compact().to_arrow() for b in plan.execute(p))
    got = pa.Table.from_batches([b for b in out if b.num_rows]).to_pandas()
    pairs = _oracle_pairs(left, right)
    nl, nr = left.num_rows, right.num_rows
    want_rows = (len(pairs) + (nl - len(set(pairs.li))) +
                 (nr - len(set(pairs.ri))))
    assert len(got) == want_rows


def test_existence_requires_build_right():
    left, right = _tables(nl=10, nr=5)
    with pytest.raises(ValueError, match="build_side"):
        BroadcastNestedLoopJoinExec(
            MemoryScanExec.from_arrow(left),
            MemoryScanExec.from_arrow(right),
            JoinType.EXISTENCE, build_side="left")
