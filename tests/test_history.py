"""Query history server (bridge/history.py): persistent event log,
deterministic replay that survives process restart, fleet rollups, the
device-utilization ledger, retention/compaction, and the HTTP surface.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from blaze_tpu import config
from blaze_tpu.bridge import history, profiling, tracing
from blaze_tpu.memory import MemManager
from blaze_tpu.serving import QueryService

from tests.test_serving import _two_stage_plan


@pytest.fixture(autouse=True)
def clean_slate():
    MemManager.init(4 << 30)
    history.reset_conf_probe()
    tracing.reset_conf_probe()
    try:
        yield
    finally:
        for opt in (config.HISTORY_ENABLE, config.HISTORY_DIR,
                    config.HISTORY_MAX_EVENTS, config.HISTORY_MAX_QUERIES,
                    config.TRACE_ENABLE, config.DAG_SINGLE_TASK_BYTES):
            config.conf.unset(opt.key)
        history.reset_conf_probe()
        tracing.stop_tracing()
        tracing.reset_conf_probe()
        MemManager.init(4 << 30)


@pytest.fixture
def hist_dir(tmp_path):
    d = str(tmp_path / "hist")
    config.conf.set(config.HISTORY_ENABLE.key, "true")
    config.conf.set(config.HISTORY_DIR.key, d)
    history.reset_conf_probe()
    return d


def _emit_full_query(qid, tenant="acme"):
    """Drive every emitter once, as the engine would."""
    history.note_admitted(qid, tenant=tenant, deadline_ms=0, mem_quota=0)
    history.note_started(qid, queued_s=0.001)
    history.note_stage(qid, sid=0, exchange="file", compute="staged",
                       tasks=2, metrics={"output_rows": 400})
    history.note_stage(qid, sid=1, exchange="result", compute="staged",
                       tasks=1, metrics={"output_rows": 200})
    history.note_stage_recovery(qid, sid=0, map_task=1)
    history.note_finished(qid, status="done", tenant=tenant, wall_s=0.25)


# -- off by default ----------------------------------------------------------

def test_disabled_by_default_writes_nothing(tmp_path):
    d = str(tmp_path / "hist")
    config.conf.set(config.HISTORY_DIR.key, d)  # dir set, enable NOT set
    history.reset_conf_probe()
    assert history.enabled() is False
    _emit_full_query("q-off")
    assert not os.path.exists(d)  # not even the directory is created


# -- event log ---------------------------------------------------------------

def test_event_log_lines_are_schema_versioned(hist_dir):
    assert history.enabled() is True
    _emit_full_query("q1")
    path = os.path.join(hist_dir, "query-q1.jsonl")
    assert os.path.exists(path)
    with open(path) as f:
        events = [json.loads(line) for line in f]
    assert [e["event"] for e in events] == [
        "admitted", "started", "stage_complete", "stage_complete",
        "stage_recovery", "finished"]
    for e in events:
        assert e["v"] == history.HISTORY_SCHEMA_VERSION
        assert e["query"] == "q1"
        assert e["event"] in history.EVENT_TYPES
        assert isinstance(e["ts"], float)


def test_qid_is_sanitized_into_filename(hist_dir):
    history.note_admitted("../../etc/passwd", tenant="t")
    names = os.listdir(hist_dir)
    assert names == ["query-.._.._etc_passwd.jsonl"]


def test_max_events_cap_drops_but_terminal_always_lands(hist_dir):
    config.conf.set(config.HISTORY_MAX_EVENTS.key, 4)
    history.note_admitted("qcap", tenant="t")
    for i in range(10):
        history.note_stage(qid := "qcap", sid=i, exchange="file",
                           compute="staged")
    history.note_finished(qid, status="done", tenant="t", wall_s=0.1)
    store = history.HistoryStore(hist_dir)
    events = store.events("qcap")
    assert len(events) == 5  # 4 capped + the terminal event
    assert events[-1]["event"] == "finished"
    assert events[-1]["events_dropped"] == 7
    s = store.summary("qcap")
    assert s["status"] == "done"
    assert s["events_dropped"] == 7


# -- replay / restart survival ----------------------------------------------

def test_summary_replay_is_bit_stable(hist_dir):
    _emit_full_query("q2", tenant="acme")
    a = history.HistoryStore(hist_dir)
    b = history.HistoryStore(hist_dir)
    assert json.dumps(a.summary("q2"), sort_keys=True) == \
        json.dumps(b.summary("q2"), sort_keys=True)
    assert json.dumps(a.rollup(), sort_keys=True) == \
        json.dumps(b.rollup(), sort_keys=True)
    s = a.summary("q2")
    assert s["schema_version"] == history.ROLLUP_SCHEMA_VERSION
    assert s["tenant"] == "acme"
    assert s["status"] == "done"
    assert s["stage_recoveries"] == 1
    assert [st["stage"] for st in s["stages"]] == [0, 1]
    assert s["attribution"]["approximate"] is True
    assert s["wall_s"] == 0.25


def test_fresh_process_replays_identical_summary(hist_dir):
    """The restart-survival acceptance: a brand-new process, sharing
    nothing but the log directory, replays byte-identical /history/<qid>
    and /history/rollup payloads."""
    _emit_full_query("q3", tenant="acme")
    here = history.HistoryStore(hist_dir)
    want_summary = json.dumps(here.summary("q3"), sort_keys=True)
    want_rollup = json.dumps(here.rollup(), sort_keys=True)
    code = (
        "import json, sys\n"
        "from blaze_tpu.bridge.history import HistoryStore\n"
        "store = HistoryStore(sys.argv[1])\n"
        "print(json.dumps(store.summary('q3'), sort_keys=True))\n"
        "print(json.dumps(store.rollup(), sort_keys=True))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code, hist_dir],
                         capture_output=True, text=True, timeout=240,
                         env=env,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    got_summary, got_rollup = out.stdout.strip().splitlines()[-2:]
    assert got_summary == want_summary
    assert got_rollup == want_rollup


# -- rollup ------------------------------------------------------------------

def test_rollup_aggregates_by_tenant_and_stage_type(hist_dir):
    _emit_full_query("qa", tenant="acme")
    _emit_full_query("qb", tenant="acme")
    _emit_full_query("qc", tenant="beta")
    r = history.HistoryStore(hist_dir).rollup()
    assert r["schema_version"] == history.ROLLUP_SCHEMA_VERSION
    assert r["queries"] == 3
    assert r["tenants"]["acme"]["queries"] == 2
    assert r["tenants"]["acme"]["completed"] == 2
    assert r["tenants"]["beta"]["queries"] == 1
    acme = r["tenants"]["acme"]
    assert acme["wall_ms_p50"] == 250.0
    assert acme["wall_ms_p99"] == 250.0
    assert set(acme["shuffle_bytes_by_tier"]) == {"device", "rss", "file"}
    # stage-type keying: 2 stages per query, split file/result exchange
    assert r["stages_by_exchange"]["file"]["stages"] == 3
    assert r["stages_by_exchange"]["result"]["stages"] == 3
    assert r["stages_by_exchange"]["file"]["output_rows"] == 3 * 400
    assert r["stages_by_compute"]["staged"]["stages"] == 6
    # every flat counter key is present, even at zero
    for k in history.rollup_counter_keys():
        assert k in r["counters"], k


def test_rollup_qps_and_failed_counts(hist_dir):
    history.note_admitted("qf", tenant="t")
    history.note_finished("qf", status="failed", tenant="t", wall_s=0.1,
                          error="ValueError: boom")
    r = history.HistoryStore(hist_dir).rollup()
    assert r["tenants"]["t"]["failed"] == 1
    assert r["tenants"]["t"]["completed"] == 0
    s = history.HistoryStore(hist_dir).summary("qf")
    assert s["error"] == "ValueError: boom"


# -- retention / compaction --------------------------------------------------

def test_prune_keeps_newest_max_queries(hist_dir):
    config.conf.set(config.HISTORY_MAX_QUERIES.key, 3)
    os.makedirs(hist_dir, exist_ok=True)
    now = time.time()
    for i in range(6):
        p = os.path.join(hist_dir, f"query-q{i}.jsonl")
        with open(p, "w") as f:
            f.write(json.dumps({"v": 1, "event": "admitted",
                                "query": f"q{i}", "ts": now}) + "\n")
        os.utime(p, (now - 60 + i, now - 60 + i))
    removed = history.prune(hist_dir)
    assert removed == 3
    assert sorted(os.listdir(hist_dir)) == [
        "query-q3.jsonl", "query-q4.jsonl", "query-q5.jsonl"]


def test_admission_triggers_retention(hist_dir):
    config.conf.set(config.HISTORY_MAX_QUERIES.key, 2)
    for i in range(4):
        history.note_admitted(f"qr{i}", tenant="t")
        time.sleep(0.01)  # distinct mtimes
    assert len(os.listdir(hist_dir)) <= 2
    assert "query-qr3.jsonl" in os.listdir(hist_dir)  # newest survives


def test_compact_preserves_summary_drops_epochs(hist_dir):
    qid = "qstream"
    history.note_admitted(qid, tenant="t")
    for epoch in range(20):
        history.note_stream_epoch(qid, epoch=epoch, rows=10, records=10,
                                  wall_ns=1000, committed=True)
    history.note_finished(qid, status="done", tenant="t", wall_s=1.0)
    store = history.HistoryStore(hist_dir)
    before = store.summary(qid)
    removed = store.compact()
    assert removed == 20
    after = store.summary(qid)
    for k in ("status", "tenant", "wall_s", "attribution"):
        assert after[k] == before[k]
    assert after["events"] == before["events"] - 20
    # a second compaction is a no-op
    assert store.compact() == 0


def test_compact_leaves_live_queries_alone(hist_dir):
    history.note_admitted("qlive", tenant="t")
    history.note_stream_epoch("qlive", epoch=0, rows=1, records=1,
                              wall_ns=1, committed=True)
    store = history.HistoryStore(hist_dir)
    assert store.compact() == 0  # no `finished` event yet
    assert len(store.events("qlive")) == 2


def test_torn_trailing_line_is_skipped(hist_dir):
    history.note_admitted("qtorn", tenant="t")
    with open(os.path.join(hist_dir, "query-qtorn.jsonl"), "a") as f:
        f.write('{"v": 1, "event": "fini')  # crash mid-append
    store = history.HistoryStore(hist_dir)
    assert len(store.events("qtorn")) == 1
    assert store.summary("qtorn")["status"] == "queued"


# -- device-utilization ledger ----------------------------------------------

_MS = 1_000_000  # ns per ms; keep synthetic times above the 1µs rounding


def _span(name, t0_ms, dur_ms, stage=None, **attrs):
    t0, dur = t0_ms * _MS, dur_ms * _MS
    r = {"name": name, "t0_ns": t0, "t1_ns": t0 + dur, "dur_ns": dur,
         "ctx": {}, "attrs": dict(attrs)}
    if stage is not None:
        r["ctx"]["stage"] = stage
    return r


def test_device_ledger_busy_gap_and_barrier():
    spans = [
        # stage 0: two device dispatches with a 100ms gap, then the
        # exchange barrier 100ms after the last device completion
        _span("stage_loop_chunk", 0, 100, stage=0),
        _span("stage_loop_chunk", 200, 100, stage=0),
        _span("rss_exchange", 400, 50, stage=0, nbytes=1024),
        # stage 1: overlapping dispatches must not double-count
        _span("device_exchange", 1000, 100, stage=1),
        _span("device_exchange", 1050, 100, stage=1),
    ]
    led = history.device_ledger(spans)
    s0 = led["stages"]["0"]
    assert s0["device_busy_s"] == pytest.approx(0.200)
    assert s0["dispatch_gap_s"] == pytest.approx(0.100)
    assert s0["barrier_idle_s"] == pytest.approx(0.100)
    assert s0["wall_s"] == pytest.approx(0.450)
    s1 = led["stages"]["1"]
    assert s1["device_busy_s"] == pytest.approx(0.150)  # union
    assert s1["dispatch_gap_s"] == 0.0
    assert led["device_busy_s"] == pytest.approx(0.350)
    assert 0.0 < led["device_utilization"] <= 1.0


def test_device_ledger_stageless_spans_are_overhead():
    led = history.device_ledger([_span("plan_compile", 0, 500)])
    assert set(led["stages"]) == {"-1"}
    assert led["stages"]["-1"]["device_spans"] == 0
    assert led["device_utilization"] == 0.0  # nothing dispatched


def test_xla_compile_instant_counts_ns_attr():
    spans = [{"name": "xla_compile", "t0_ns": 100 * _MS,
              "t1_ns": 100 * _MS, "dur_ns": 0, "ctx": {"stage": 0},
              "attrs": {"ns": 400 * _MS}}]
    led = history.device_ledger(spans)
    assert led["stages"]["0"]["device_busy_s"] == pytest.approx(0.400)


def test_finished_event_embeds_bottleneck_and_advisor(hist_dir, tmp_path):
    from blaze_tpu.plan import statstore
    config.conf.set(config.TRACE_ENABLE.key, "on")
    tracing.reset_conf_probe()
    config.conf.set(config.STATS_ENABLE.key, "on")
    config.conf.set(config.STATS_DIR.key, str(tmp_path / "stats"))
    statstore.reset_conf_probe()
    try:
        with tracing.execution_context(query="qbn"):
            with tracing.span("task", stage=0):
                time.sleep(0.002)
        statstore.ingest({"fingerprint": "fp-bn", "wall_s": 0.01,
                          "task_ns": [], "counters": {},
                          "fallback_reasons": {"stage_loop": 2},
                          "stages": []})
        history.note_admitted("qbn", tenant="t")
        history.note_finished("qbn", status="done", tenant="t",
                              wall_s=0.01, fingerprint="fp-bn")
        s = history.HistoryStore(hist_dir).summary("qbn")
        assert s["fingerprint"] == "fp-bn"
        bn = s["bottleneck"]
        assert bn is not None and bn["v"] == 1
        assert sum(bn["categories"].values()) == pytest.approx(
            bn["wall_s"], rel=0.01)
        assert any(f["kind"] == "host_eviction" for f in s["advisor"])
    finally:
        for opt in (config.STATS_ENABLE, config.STATS_DIR):
            config.conf.unset(opt.key)
        statstore.reset_conf_probe()


def test_device_ledger_zero_exchange_stage_has_no_barrier():
    # single-stage plans never emit exchange-tier spans: the barrier
    # must report 0, never negative, never raise
    spans = [_span("stage_loop_chunk", 0, 100, stage=0),
             _span("task", 0, 150, stage=0)]
    led = history.device_ledger(spans)
    s0 = led["stages"]["0"]
    assert s0["barrier_idle_s"] == 0.0
    assert s0["device_busy_s"] == pytest.approx(0.100)
    assert led["barrier_idle_s"] == 0.0


def test_device_ledger_streaming_epoch_only_trace():
    # a streaming query's trace is stream_epoch spans with no device
    # dispatch and no exchange at all
    spans = [_span("stream_epoch", i * 100, 80, stage=0, epoch=i)
             for i in range(3)]
    led = history.device_ledger(spans)
    s0 = led["stages"]["0"]
    assert s0["device_spans"] == 0
    assert s0["barrier_idle_s"] == 0.0
    assert s0["dispatch_gap_s"] == 0.0
    assert s0["wall_s"] == pytest.approx(0.280)
    assert led["device_utilization"] == 0.0


def test_device_ledger_empty_and_malformed_traces():
    assert history.device_ledger([])["stages"] == {}
    led = history.device_ledger([
        None, "span", 7,
        {"name": "task", "t0_ns": "NaNish", "ctx": {"stage": 0}},
        {"name": "device_exchange", "t0_ns": 0, "t1_ns": None,
         "dur_ns": None, "ctx": "not-a-dict", "attrs": ["nope"]},
        _span("device_exchange", 0, 50, stage=1),
    ])
    # the one well-formed span survives; nothing negative anywhere
    assert led["stages"]["1"]["device_busy_s"] == pytest.approx(0.050)
    for row in led["stages"].values():
        for k in ("wall_s", "device_busy_s", "dispatch_gap_s",
                  "barrier_idle_s"):
            assert row[k] >= 0.0


# -- end-to-end: QueryService + HTTP surface ---------------------------------

def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, json.loads(r.read().decode())


def test_service_query_lands_in_history_and_http(hist_dir, tmp_path):
    config.conf.set(config.TRACE_ENABLE.key, "on")
    tracing.reset_conf_probe()
    # force staged execution so stage_complete events exist on this
    # small input (the single-task fast path never assigns placements)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    svc = QueryService()
    try:
        h = svc.submit(_two_stage_plan(tmp_path, n=2_000),
                       tenant="acme", query_id="qe2e")
        h.result(60)
    finally:
        svc.shutdown()

    port = profiling.start_http_service()
    try:
        code, listing = _get(port, "/history")
        assert code == 200
        assert any(s["query_id"] == "qe2e" and s["status"] == "done"
                   for s in listing)
        code, s = _get(port, "/history/qe2e")
        assert code == 200
        assert s["status"] == "done"
        assert s["tenant"] == "acme"
        assert s["stages"], "no stage_complete events replayed"
        assert s["metric_tree"] is not None
        assert s["attribution"]["counters"]
        assert s["device_ledger"] is not None  # tracing was on
        code, r = _get(port, "/history/rollup")
        assert code == 200
        assert r["tenants"]["acme"]["completed"] == 1
        assert r["stages_by_exchange"]
        # unknown qid 404s with a hint
        try:
            _get(port, "/history/nope")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:
            raise AssertionError("/history/nope unexpectedly succeeded")
    finally:
        profiling.stop_http_service()
