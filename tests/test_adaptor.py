"""Engine-adaptor SPI (the AuronAdaptor abstraction, VERDICT r4 §2.3
"AuronAdaptor SPI: partial — callbacks are module-level").

One `EngineAdaptor` subclass per host engine replaces the loose
module-level hooks; `set_adaptor` wires conf resolution, the
cooperative task-kill probe, and UDF resolution through it, and the
C-ABI callback route surfaces as a `CallbackAdaptor` so
`get_adaptor()` answers for either installation path."""

import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.bridge import adaptor as A
from blaze_tpu.bridge import host_callbacks
from blaze_tpu.bridge.resource import get_resource, put_resource
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan
from blaze_tpu.plan.types import schema_to_dict
from blaze_tpu.schema import Schema


@pytest.fixture(autouse=True)
def clean():
    MemManager.init(1 << 30)
    yield
    A.set_adaptor(None)
    A._providers.clear()


class _SparkishAdaptor(A.EngineAdaptor):
    name = "sparkish"

    def __init__(self):
        self.confs = {"spark.sql.ansi.enabled": "false",
                      "auron.batch.size": "4096"}
        self.killed = False
        self.udfs = {"double_it": lambda col: pa.compute.multiply(col, 2)}

    def conf_get(self, key):
        return self.confs.get(key)

    def is_task_running(self, stage_id, partition_id):
        return not self.killed

    def udf_wrapper_context(self, name):
        return self.udfs.get(name)


def test_adaptor_wires_conf_provider():
    A.set_adaptor(_SparkishAdaptor())
    # host conf resolution flows through the adaptor (memoized like the
    # reference's lazy define_conf! proxies)
    assert config.BATCH_SIZE.get() == 4096


def test_adaptor_resolves_udfs_through_spi():
    A.set_adaptor(_SparkishAdaptor())
    fn = get_resource("udf://double_it")
    assert fn is not None
    out = fn(pa.array([1, 2, 3]))
    assert out.to_pylist() == [2, 4, 6]


def test_adaptor_task_probe_kills_cooperatively():
    from blaze_tpu.bridge.context import TaskKilledError, current_task
    ad = _SparkishAdaptor()
    A.set_adaptor(ad)
    current_task().check_running()  # alive
    ad.killed = True
    with pytest.raises(TaskKilledError):
        current_task().check_running()
    ad.killed = False


def test_adaptor_runs_a_real_plan():
    A.set_adaptor(_SparkishAdaptor())
    t = pa.table({"x": pa.array([1, 2, 3])})
    put_resource("adapt://t", t)
    ir = {"kind": "project",
          "exprs": [{"kind": "udf", "name": "double_it",
                     "args": [{"kind": "column", "index": 0}],
                     "type": {"id": "int64"}}],
          "names": ["y"],
          "input": {"kind": "memory_scan", "resource_id": "adapt://t",
                    "schema": schema_to_dict(Schema.from_arrow(t.schema)),
                    "num_partitions": 1}}
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in create_plan(ir).execute(0)])
    assert out.column(0).to_pylist() == [2, 4, 6]


def test_provider_registry_selects_by_env(monkeypatch):
    A.register_provider("one", lambda: _SparkishAdaptor())

    class _Other(A.EngineAdaptor):
        name = "other"
    A.register_provider("two", lambda: _Other())
    monkeypatch.setenv("BLAZE_TPU_ADAPTOR", "two")
    got = A.get_adaptor()
    assert got.name == "other"


def test_headless_default_exists():
    # unlike the JVM reference (IllegalStateException without a
    # provider), embedded Python use gets a working default
    got = A.get_adaptor()
    assert isinstance(got, A.EngineAdaptor)
    assert got.is_task_running(0, 0)
    assert got.conf_get("anything") is None


def test_c_abi_route_surfaces_as_callback_adaptor():
    host_callbacks.install({"conf_get": None})  # minimal python install
    try:
        got = A.get_adaptor()
        assert isinstance(got, A.CallbackAdaptor)
        assert got.name == "c-abi-host"
    finally:
        host_callbacks.uninstall()


def test_spill_factory_clears_on_adaptor_switch():
    """Switching to an adaptor WITHOUT a spill factory must clear the
    previous one (stale-engine spills otherwise)."""
    from blaze_tpu.memory import spill as spill_mod
    sentinel = object()

    class WithSpill(A.EngineAdaptor):
        def on_heap_spill_factory(self):
            return sentinel
    A.set_adaptor(WithSpill())
    assert spill_mod._host_spill_factory is sentinel
    A.set_adaptor(A.EngineAdaptor())
    assert spill_mod._host_spill_factory is None
