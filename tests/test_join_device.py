"""Device join-probe kernels (kernels/join.py): jit'd match counting +
scan-based bounded pair expansion — the no-per-batch-host-loop probe the
reference does natively (ref joins/join_hash_map.rs:277, VERDICT r3 #2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.kernels.join import (build_runs, expand_pairs,
                                    probe_counts, probe_expand_device)


def _naive_pairs(build_hashes, probe_hashes, probe_null):
    p_idx, b_idx = [], []
    for i, (h, nn) in enumerate(zip(probe_hashes, probe_null)):
        if nn:
            continue
        for j, bh in enumerate(build_hashes):
            if bh == h:
                p_idx.append(i)
                b_idx.append(j)
    return np.array(p_idx, dtype=np.int64), np.array(b_idx, dtype=np.int64)


def test_probe_expand_matches_naive():
    rng = np.random.default_rng(0)
    build = rng.integers(0, 40, 300).astype(np.int64)
    probe = rng.integers(0, 60, 500).astype(np.int64)
    null = rng.random(500) < 0.1
    order = np.argsort(build, kind="stable")
    sh = build[order]
    uh, start, count = build_runs(sh)
    p, b = probe_expand_device(jnp.asarray(uh), jnp.asarray(start),
                               jnp.asarray(count), order,
                               jnp.asarray(probe), jnp.asarray(null))
    want_p, want_b = _naive_pairs(build, probe, null)
    got = sorted(zip(p.tolist(), b.tolist()))
    want = sorted(zip(want_p.tolist(), want_b.tolist()))
    assert got == want


def test_expansion_is_one_traced_program_no_host_loop():
    """The pair expansion must trace to ONE XLA program: data-dependent
    work happens via scan/scatter INSIDE the program, not a Python loop
    over rows.  make_jaxpr succeeding over abstract tracers proves no
    per-row host iteration exists on the path."""
    n = 64
    jaxpr = jax.make_jaxpr(
        lambda s, c: expand_pairs(s, c, 256))(
        jnp.zeros(n, jnp.int64), jnp.ones(n, jnp.int64))
    assert jaxpr is not None  # traced fully abstract: no host loops
    jaxpr2 = jax.make_jaxpr(probe_counts)(
        jnp.arange(8, dtype=jnp.int64), jnp.zeros(8, jnp.int64),
        jnp.ones(8, jnp.int64), jnp.arange(32, dtype=jnp.int64),
        jnp.zeros(32, bool))
    assert jaxpr2 is not None


def test_overflow_grows_bucket():
    # every probe row matches every build row: total = 64*64 = 4096 > 1024
    build = np.zeros(64, dtype=np.int64)
    probe = np.zeros(64, dtype=np.int64)
    order = np.argsort(build, kind="stable")
    uh, start, count = build_runs(build[order])
    p, b = probe_expand_device(jnp.asarray(uh), jnp.asarray(start),
                               jnp.asarray(count), order,
                               jnp.asarray(probe),
                               jnp.zeros(64, dtype=bool))
    assert len(p) == 64 * 64
    assert len(np.unique(p * 64 + b)) == 64 * 64


def test_joinmap_device_path_equals_host_path(monkeypatch):
    """JoinMap.lookup through the jit'd device kernels must produce the
    same verified pairs as the Arrow/numpy host path."""
    from blaze_tpu.exprs import col
    from blaze_tpu.ops.joins.exec import JoinMap, _device_hash_keys
    from blaze_tpu.schema import Schema
    rng = np.random.default_rng(1)
    build_t = pa.table({"k": pa.array(rng.integers(0, 50, 400)),
                        "v": pa.array(rng.random(400))})
    probe_t = pa.table({"k": pa.array(
        np.where(rng.random(800) < 0.05, None,
                 rng.integers(0, 70, 800)).tolist(), type=pa.int64())})
    schema = Schema.from_arrow(build_t.schema)

    def pairs():
        from blaze_tpu.batch import ColumnBatch
        jmap = JoinMap(build_t, [col(0, "k")], schema)
        cb = ColumnBatch.from_arrow(probe_t)
        h, nn, keys = _device_hash_keys(cb, [col(0, "k")])
        p, b = jmap.lookup(h, nn, keys)
        return sorted(zip(np.asarray(p).tolist(), np.asarray(b).tolist()))

    host = pairs()
    import blaze_tpu.bridge.placement as P
    monkeypatch.setattr(P, "host_resident", lambda: False)
    dev = pairs()
    assert host == dev and len(host) > 0


def test_float_key_normalization_all_paths():
    """-0.0 joins 0.0 and NaN joins NaN on BOTH the Acero host path and
    the vectorized JoinMap path (Spark NormalizeFloatingNumbers runs
    upstream of join hashing); HashPartitioning sends the variants to
    one reducer."""
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.ops.joins import JoinType
    from blaze_tpu.ops.joins.exec import ShuffledHashJoinExec
    from blaze_tpu.batch import ColumnBatch
    from blaze_tpu.shuffle import HashPartitioning

    left = pa.table({"lk": pa.array([-0.0, float("nan")]),
                     "lv": pa.array([1, 2], type=pa.int64())})
    right = pa.table({"rk": pa.array([0.0, float("nan")]),
                      "rv": pa.array([10, 20], type=pa.int64())})

    def rows(join):
        out = []
        for p in range(join.num_partitions):
            out.extend(b.compact().to_arrow() for b in join.execute(p))
        t = pa.Table.from_batches([b for b in out if b.num_rows])
        return sorted(t.column("lv").to_pylist())

    def build():
        return ShuffledHashJoinExec(
            MemoryScanExec.from_arrow(left),
            MemoryScanExec.from_arrow(right),
            [col(0)], [col(0)], JoinType.INNER)

    assert rows(build()) == [1, 2]  # Acero host path
    import blaze_tpu.bridge.placement as P
    orig = P.host_resident
    P.host_resident = lambda: False
    try:
        assert rows(build()) == [1, 2]  # jit'd JoinMap path
    finally:
        P.host_resident = orig

    # partitioning: -0.0 vs 0.0 and both NaN encodings -> same partition
    hp = HashPartitioning([col(0)], 4)
    pos = ColumnBatch.from_arrow(pa.table({"k": pa.array([0.0, -0.0])}))
    pids = hp.partition_ids(pos)
    assert pids[0] == pids[1]
    nans = ColumnBatch.from_arrow(pa.table(
        {"k": pa.array(np.array([np.nan, -np.nan]))}))
    pids2 = hp.partition_ids(nans)
    assert pids2[0] == pids2[1]
