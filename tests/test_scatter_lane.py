"""Scatter/hash lane kernels (ISSUE 9): the interpret-mode Pallas
kernels must be BITWISE identical to the scatter formulations they
replace, across dtypes, NULL masks, -0.0/NaN bit patterns, masked rows,
and overflow at capacity — and every fallback (knob off, VMEM decline,
injected fault) must land on the verified scatter lane losslessly.

Property style: seeded trial loops (no hypothesis in the image), each
trial drawing keys/masks/values from a fresh generator so tier-1 walks a
different corner of the space per seed while staying reproducible."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.kernels import hash_update, radix
from blaze_tpu.kernels import lane as lane_mod
from blaze_tpu.parallel.collective import _dest_slots
from blaze_tpu.parallel.stage import (hash_agg_step, init_hash_carry,
                                      rehash_carry)

pytestmark = pytest.mark.pallas


def bits_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return (a.shape == b.shape and a.dtype == b.dtype
            and a.tobytes() == b.tobytes())


def carries_bit_identical(ca, cb):
    la = jax.tree_util.tree_leaves(ca)
    lb = jax.tree_util.tree_leaves(cb)
    return len(la) == len(lb) and all(
        bits_equal(a, b) for a, b in zip(la, lb))


def _nan_payloads(n, rng):
    """float64 NaNs with DIFFERENT bit patterns: quiet, payload-bearing,
    and negative-sign — grouping must normalize them into one group on
    every lane."""
    pats = np.array([0x7FF8000000000000, 0x7FF8000000000001,
                     0xFFF8000000000099], dtype=np.uint64)
    return pats[rng.integers(0, 3, n)].view(np.float64)


def _trial_key_col(rng, n, dtype):
    if dtype == np.float64 or dtype == np.float32:
        d = (rng.integers(0, 300, n) - 150).astype(dtype)
        zero = rng.random(n) < 0.08
        d = np.where(zero, np.where(rng.random(n) < 0.5, 0.0, -0.0
                                    ).astype(dtype), d)
        nan = rng.random(n) < 0.08
        if dtype == np.float64:
            d = np.where(nan, _nan_payloads(n, rng), d)
        else:
            d = np.where(nan, np.float32(np.nan), d)
    else:
        d = rng.integers(-1000, 1000, n).astype(dtype)
    v = rng.random(n) > 0.15  # SQL NULL keys: still group together
    return jnp.asarray(d), jnp.asarray(v)


def _step_both(carry_args, key_cols, agg_specs, mask):
    outs = {}
    for lane in ("interpret", "scatter"):
        c = init_hash_carry(*carry_args)
        outs[lane] = hash_agg_step(c, key_cols, agg_specs, mask,
                                   lane=lane)
    return outs["interpret"], outs["scatter"]


@pytest.mark.parametrize("dtype", [np.int64, np.int32, np.float64,
                                   np.float32])
def test_hash_step_parity_across_dtypes(dtype):
    n, S = 1024, 1 << 11
    for seed in range(3):
        rng = np.random.default_rng(seed)
        kd, kv = _trial_key_col(rng, n, dtype)
        vals = jnp.asarray(rng.random(n))
        av = jnp.asarray(rng.random(n) > 0.2)
        cnt = jnp.asarray(rng.integers(0, 5, n).astype(np.int64))
        mask = jnp.asarray(rng.random(n) > 0.25)
        (ca, oa, ga), (cb, ob, gb) = _step_both(
            ([jnp.dtype(dtype)], ["sum", "min", "max", "count"],
             (jnp.float64, jnp.float64, jnp.float64, jnp.int64), S),
            [(kd, kv)],
            [("sum", vals, av), ("min", vals, av), ("max", vals, av),
             ("count", cnt, av)], mask)
        assert int(oa) == int(ob) and int(ga) == int(gb)
        assert carries_bit_identical(ca, cb), \
            f"lane divergence at dtype={dtype} seed={seed}"


def test_hash_step_parity_multi_key():
    n, S = 1024, 1 << 11
    for seed in range(3):
        rng = np.random.default_rng(100 + seed)
        k1 = _trial_key_col(rng, n, np.int64)
        k2 = _trial_key_col(rng, n, np.float64)
        vals = jnp.asarray(rng.random(n))
        av = jnp.asarray(rng.random(n) > 0.2)
        mask = jnp.asarray(rng.random(n) > 0.25)
        (ca, oa, ga), (cb, ob, gb) = _step_both(
            ([jnp.int64, jnp.float64], ["sum"], (jnp.float64,), S),
            [k1, k2], [("sum", vals, av)], mask)
        assert int(oa) == int(ob) and int(ga) == int(gb)
        assert carries_bit_identical(ca, cb)


def test_hash_step_overflow_at_capacity_is_atomic():
    # S=32 with ~300 distinct keys: placement MUST overflow; both lanes
    # return the overflow count AND the untouched pre-state carry
    n, S = 512, 32
    rng = np.random.default_rng(9)
    kd = jnp.asarray(rng.integers(0, 300, n).astype(np.int64))
    kv = jnp.asarray(np.ones(n, bool))
    vals = jnp.asarray(rng.random(n))
    av = kv
    mask = kv
    (ca, oa, _), (cb, ob, _) = _step_both(
        ([jnp.int64], ["sum"], (jnp.float64,), S),
        [(kd, kv)], [("sum", vals, av)], mask)
    assert int(oa) > 0 and int(oa) == int(ob)
    assert carries_bit_identical(ca, cb)
    # atomic: the returned carry is the original empty table
    assert int(jnp.sum(ca.used)) == 0


def test_rehash_parity():
    n, S = 1024, 1 << 10
    rng = np.random.default_rng(21)
    kd = jnp.asarray(rng.integers(0, 400, n).astype(np.int64))
    kv = jnp.asarray(rng.random(n) > 0.1)
    vals = jnp.asarray(rng.random(n))
    av = jnp.asarray(rng.random(n) > 0.1)
    mask = jnp.asarray(np.ones(n, bool))
    seeded, _, _ = hash_agg_step(
        init_hash_carry([jnp.int64], ["sum"], (jnp.float64,), S),
        [(kd, kv)], [("sum", vals, av)], mask, lane="scatter")
    outs = {}
    for lane in ("interpret", "scatter"):
        grown, ovf, ng = rehash_carry(seeded, ["sum"], 4 * S, lane=lane)
        outs[lane] = (grown, int(ovf), int(ng))
    assert outs["interpret"][1:] == outs["scatter"][1:]
    assert carries_bit_identical(outs["interpret"][0],
                                 outs["scatter"][0])


# -- radix partition kernel -------------------------------------------------

def test_radix_dest_slots_buffers_bit_identical():
    # the scattered per-destination buffers (what all_to_all actually
    # ships) must match the argsort formulation's buffers exactly,
    # including parked pids and capacity overflow routing
    for seed, (P, cap) in ((0, (4, 512)), (1, (7, 64)), (2, (16, 128))):
        rng = np.random.default_rng(seed)
        n = 2000
        pid = jnp.asarray(
            rng.integers(0, P + 2, n).astype(np.int64))  # some parked
        col = jnp.asarray(rng.random(n))

        def buffers(lane):
            order, dest, ovf = _dest_slots(pid, P, cap, lane=lane)
            sc = jnp.take(col, order) if order is not None else col
            buf = jnp.zeros((P + 1, cap + 1), dtype=col.dtype)
            return buf.at[dest].set(sc, mode="drop")[:P, :cap], int(ovf)

        buf_k, ovf_k = buffers("interpret")
        buf_s, ovf_s = buffers("scatter")
        assert ovf_k == ovf_s
        assert bits_equal(buf_k, buf_s), f"seed={seed} P={P} cap={cap}"


def test_partition_order_matches_stable_argsort():
    for seed, n in ((0, 1), (1, 777), (2, 4096), (3, 5000)):
        rng = np.random.default_rng(seed)
        pids = rng.integers(0, 9, n).astype(np.int64)
        order, starts, ends = radix.partition_order(pids, 9,
                                                    interpret=True)
        ref = np.argsort(pids, kind="stable")
        assert np.array_equal(order, ref)
        assert np.array_equal(
            starts, np.searchsorted(pids[ref], np.arange(9), "left"))
        assert np.array_equal(
            ends, np.searchsorted(pids[ref], np.arange(9), "right"))
    # empty batch contract
    order, starts, ends = radix.partition_order(
        np.zeros(0, np.int64), 3, interpret=True)
    assert len(order) == 0 and np.array_equal(ends, np.zeros(3))


# -- lane resolution, declines, faults --------------------------------------

@pytest.fixture
def _clean_lane():
    faults.clear()
    yield
    faults.clear()
    config.conf.unset(config.KERNELS_PALLAS.key)
    config.conf.unset(config.KERNELS_PALLAS_VMEM_BUDGET.key)


def test_lane_knob_resolution(_clean_lane):
    config.conf.set(config.KERNELS_PALLAS.key, "off")
    assert lane_mod.resolve("hash") == "scatter"
    config.conf.set(config.KERNELS_PALLAS.key, "on")
    want = "pallas" if jax.default_backend() == "tpu" else "interpret"
    assert lane_mod.resolve("hash") == want
    config.conf.set(config.KERNELS_PALLAS.key, "auto")
    want = "pallas" if jax.default_backend() == "tpu" else "scatter"
    assert lane_mod.resolve("partition") == want


def test_vmem_decline_falls_back_to_scatter(_clean_lane):
    # shrink the budget below any real footprint: place_rows declines,
    # hash_agg_step lands on the scatter lane, results stay identical
    n, S = 512, 1 << 10
    rng = np.random.default_rng(3)
    kd = jnp.asarray(rng.integers(0, 100, n).astype(np.int64))
    kv = jnp.asarray(np.ones(n, bool))
    vals = jnp.asarray(rng.random(n))
    ref, _, _ = hash_agg_step(
        init_hash_carry([jnp.int64], ["sum"], (jnp.float64,), S),
        [(kd, kv)], [("sum", vals, kv)], kv, lane="scatter")
    config.conf.set(config.KERNELS_PALLAS_VMEM_BUDGET.key, 1024)
    before = xla_stats.snapshot()
    got, _, _ = hash_agg_step(
        init_hash_carry([jnp.int64], ["sum"], (jnp.float64,), S),
        [(kd, kv)], [("sum", vals, kv)], kv, lane="interpret")
    d = xla_stats.delta(before)
    assert d["scatter_lane_declines"] >= 1
    assert carries_bit_identical(ref, got)
    assert hash_update.vmem_estimate(n, S, 3) > 1024


def test_fault_site_forces_lossless_scatter_fallback(_clean_lane):
    # chaos at the pallas-kernel site: resolve() swallows the injected
    # fault, notes it, and degrades to the scatter lane — never an error
    config.conf.set(config.KERNELS_PALLAS.key, "on")
    faults.configure("pallas-kernel=1.0", seed=1)  # always fire
    before = xla_stats.snapshot()
    assert lane_mod.resolve("hash") == "scatter"
    d = xla_stats.delta(before)
    assert d["scatter_lane_fault_fallbacks"] == 1
    assert d["scatter_lane_hash_scatter"] == 1
    faults.clear()
    assert lane_mod.resolve("hash") in ("pallas", "interpret")


def test_fault_site_chaos_results_identical(_clean_lane):
    # seeded intermittent chaos: some steps take the kernel lane, some
    # are forced onto scatter mid-stream — the folded table must be
    # bitwise the same as an all-scatter run
    n, S = 512, 1 << 10
    rng = np.random.default_rng(17)
    batches = []
    for _ in range(4):
        kd = jnp.asarray(rng.integers(0, 200, n).astype(np.int64))
        kv = jnp.asarray(rng.random(n) > 0.1)
        vals = jnp.asarray(rng.random(n))
        batches.append((kd, kv, vals))

    def run():
        c = init_hash_carry([jnp.int64], ["sum"], (jnp.float64,), S)
        for kd, kv, vals in batches:
            c, ovf, _ = hash_agg_step(c, [(kd, kv)],
                                      [("sum", vals, kv)], kv)
            assert int(ovf) == 0
        return c

    config.conf.set(config.KERNELS_PALLAS.key, "off")
    ref = run()
    config.conf.set(config.KERNELS_PALLAS.key, "on")
    faults.configure("pallas-kernel@2", seed=5)  # fire on the 2nd visit
    try:
        got = run()
    finally:
        faults.clear()
    assert carries_bit_identical(ref, got)


def test_knob_on_off_jit_fold_bit_identical(_clean_lane):
    # end-to-end shape: the jit'd fori fold (runtime/loop.py's pattern)
    # with the lane threaded through the cache key — flip the knob, get
    # a fresh trace, identical bits
    n, S = 2048, 1 << 11
    rng = np.random.default_rng(31)
    kd = jnp.asarray(rng.integers(0, 500, n).astype(np.int64))
    kv = jnp.asarray(rng.random(n) > 0.1)
    vals = jnp.asarray(rng.random(n))

    def fold(lane):
        @jax.jit
        def run(c, kd, kv, ad):
            def body(_i, c):
                return hash_agg_step(c, [(kd, kv)], [("sum", ad, kv)],
                                     kv, lane=lane)[0]
            return jax.lax.fori_loop(0, 3, body, c)
        return run(init_hash_carry([jnp.int64], ["sum"],
                                   (jnp.float64,), S), kd, kv, vals)

    assert carries_bit_identical(fold("interpret"), fold("scatter"))
