"""Tier-1 conformance: every ExecutionPlan subclass is auto-metered and
emits the standard baseline metric set.

Guards the profiler's core invariant — a new operator cannot silently
opt out of `output_rows`/`elapsed_compute_ns`/... accounting, because
`ExecutionPlan.__init_subclass__` wraps each subclass-own
`execute`/`arrow_batches` and `MetricNode` pre-seeds the baseline keys.
"""

import importlib

import pyarrow as pa
import pytest

from blaze_tpu.bridge.metrics import BASELINE_METRICS, MetricNode
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.memory import MemManager
from blaze_tpu.ops.base import ExecutionPlan

# import the operator surface broadly so __subclasses__() sees everything
_OP_MODULES = [
    "blaze_tpu.ops",
    "blaze_tpu.ops.agg.exec",
    "blaze_tpu.ops.basic",
    "blaze_tpu.ops.generate",
    "blaze_tpu.ops.joins.bnlj",
    "blaze_tpu.ops.joins.exec",
    "blaze_tpu.ops.kafka",
    "blaze_tpu.ops.orc",
    "blaze_tpu.ops.scan",
    "blaze_tpu.ops.sink",
    "blaze_tpu.ops.sort",
    "blaze_tpu.ops.window",
    "blaze_tpu.plan.fused",
    "blaze_tpu.shuffle.exchange",
    "blaze_tpu.shuffle.reader",
    "blaze_tpu.shuffle.writer",
]
for _m in _OP_MODULES:
    importlib.import_module(_m)


def _all_subclasses(cls):
    out = []
    for sub in cls.__subclasses__():
        out.append(sub)
        out.extend(_all_subclasses(sub))
    return out


ALL_PLANS = sorted(_all_subclasses(ExecutionPlan), key=lambda c: c.__name__)


def test_operator_surface_is_nontrivial():
    # the conformance sweep below is vacuous if imports stop reaching ops
    assert len(ALL_PLANS) >= 20, [c.__name__ for c in ALL_PLANS]


@pytest.mark.parametrize("cls", ALL_PLANS, ids=lambda c: c.__name__)
def test_every_plan_subclass_is_metered(cls):
    for attr in ("execute", "arrow_batches"):
        fn = getattr(cls, attr, None)
        if fn is None or fn is getattr(ExecutionPlan, attr, None):
            continue  # inherited from the (abstract) base — base drives it
        assert getattr(fn, "_blaze_metered", False), (
            f"{cls.__name__}.{attr} is not auto-metered; did it bypass "
            f"ExecutionPlan.__init_subclass__ (e.g. assigned after class "
            f"creation)?")


def test_metric_nodes_preseed_baseline_set():
    from blaze_tpu.ops.basic import FilterExec, ProjectExec
    from blaze_tpu.ops.scan import MemoryScanExec

    MemManager.init(4 << 30)
    t = pa.table({"a": list(range(100))})
    plan = ProjectExec(
        FilterExec(MemoryScanExec.from_arrow(t),
                   [BinaryExpr("<", col(0), lit(50))]),
        [col(0)], ["a"])

    def check(node, must_be_live):
        tree = node.metrics
        label = type(node).__name__
        assert isinstance(tree, MetricNode)
        for m in BASELINE_METRICS:
            assert m in tree.values, f"{label} missing {m}"
        if must_be_live:
            assert tree.values["output_rows"] > 0, label
            assert tree.values["elapsed_compute_ns"] > 0, label
        for c in node.children:
            check(c, must_be_live)

    check(plan, must_be_live=False)  # pre-run: keys exist, all zero
    rows = sum(b.num_rows for b in plan.execute(0))
    assert rows == 50
    check(plan, must_be_live=True)


def test_prometheus_exposition_covers_runtime_families():
    """/metrics.prom conformance: every runtime counter family added
    since the streaming/worker/speculation/observability PRs must render
    — a renamed xla_stats key cannot silently drop off the scrape."""
    from blaze_tpu.bridge import profiling, xla_stats

    MemManager.init(4 << 30)
    # touch each plane so at least one sample exists per family
    xla_stats.note_task_duration(25_000_000)
    xla_stats.note_wave_wall(50_000_000)
    text = profiling.prometheus_text()

    for family in ("blaze_stream_", "blaze_worker_", "blaze_speculation_",
                   "blaze_obs_"):
        assert any(line.startswith(family) and "_total" in line
                   for line in text.splitlines()), f"missing {family}*"
    # every key xla_stats exposes for these planes is present by name
    for k in xla_stats.worker_stats():
        assert f"blaze_{k}_total" in text, k
    for k in xla_stats.speculation_stats():
        assert f"blaze_{k}_total" in text, k
    for k in xla_stats.obs_stats():
        assert f"blaze_{k}_total" in text, k
    for k in xla_stats.stream_stats():
        want = (f"blaze_{k[:-5]}" if k.endswith("_last")
                else f"blaze_{k}_total")
        assert want in text, k


def test_prometheus_scrape_is_deterministic_and_self_describing():
    """Two back-to-back scrapes must be byte-identical (the exposition
    carries no per-scrape state), and every sample family must be
    preceded by its # HELP and # TYPE metadata exactly once — the
    mutable-default `seen` set used to leak across scrapes and drop
    metadata from the second one."""
    from blaze_tpu.bridge import profiling, xla_stats

    MemManager.init(4 << 30)
    xla_stats.note_task_duration(25_000_000)
    xla_stats.note_wave_wall(50_000_000)
    first = profiling.prometheus_text()
    second = profiling.prometheus_text()
    assert first == second

    for text in (first, second):
        lines = text.splitlines()
        helps = {ln.split()[2] for ln in lines if ln.startswith("# HELP")}
        types = {ln.split()[2] for ln in lines if ln.startswith("# TYPE")}
        families = set()
        for ln in lines:
            if not ln or ln.startswith("#"):
                continue
            name = ln.split("{", 1)[0].split(" ", 1)[0]
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and any(
                        t + suffix == name for t in types):
                    name = name[:-len(suffix)]
                    break
            families.add(name)
        missing_help = families - helps
        missing_type = families - types
        assert not missing_help, f"families without HELP: {missing_help}"
        assert not missing_type, f"families without TYPE: {missing_type}"
        # metadata emitted exactly once per family
        type_lines = [ln.split()[2] for ln in lines
                      if ln.startswith("# TYPE")]
        assert len(type_lines) == len(set(type_lines))
        # monotonically-accumulated families declare themselves counters
        for ln in lines:
            if ln.startswith("# TYPE") and ln.split()[2].endswith("_total"):
                assert ln.split()[3] == "counter", ln


def test_prometheus_histograms_render_cumulative_buckets():
    from blaze_tpu.bridge import profiling, xla_stats

    MemManager.init(4 << 30)
    xla_stats.note_task_duration(25_000_000)   # 25ms sample
    xla_stats.note_wave_wall(2_000_000_000)    # 2s sample
    text = profiling.prometheus_text()
    for name in ("blaze_task_duration_seconds", "blaze_wave_wall_seconds"):
        assert f"# TYPE {name} histogram" in text
        lines = [ln for ln in text.splitlines() if ln.startswith(name)]
        buckets = [ln for ln in lines if "_bucket{" in ln]
        assert buckets and any('le="+Inf"' in ln for ln in buckets)
        assert any(ln.startswith(f"{name}_sum ") for ln in lines)
        assert any(ln.startswith(f"{name}_count ") for ln in lines)
        # cumulative: counts never decrease as le grows
        counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
        assert counts == sorted(counts)
