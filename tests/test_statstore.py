"""Per-fingerprint statistics store (plan/statstore.py) and the
advisor built on it (plan/advisor.py): sketch determinism, merged
priors across runs, deterministic replay, retention, disabled-path
hygiene, and the findings catalog.
"""

import json
import os
import subprocess
import sys

import pytest

from blaze_tpu import config
from blaze_tpu.plan import advisor, statstore


@pytest.fixture(autouse=True)
def clean_probe():
    statstore.reset_conf_probe()
    try:
        yield
    finally:
        for opt in (config.STATS_ENABLE, config.STATS_DIR,
                    config.STATS_MAX_FINGERPRINTS,
                    config.STATS_SKETCH_CENTROIDS):
            config.conf.unset(opt.key)
        statstore.reset_conf_probe()


@pytest.fixture
def stats_on(tmp_path):
    d = str(tmp_path / "stats")
    config.conf.set(config.STATS_ENABLE.key, "on")
    config.conf.set(config.STATS_DIR.key, d)
    statstore.reset_conf_probe()
    return d


def _obs(fp="fp-a", wall=1.0, **over):
    obs = {
        "fingerprint": fp,
        "wall_s": wall,
        "task_ns": [1_000_000, 2_000_000, 4_000_000],
        "counters": {"partial_agg_probe_rows": 100,
                     "partial_agg_probe_groups": 40,
                     "expr_programs_built": 2,
                     "expr_program_cache_hits": 6},
        "fallback_reasons": {},
        "stages": [{"fingerprint": "st-0", "sid": 0, "tasks": 2,
                    "partitions": 4,
                    "partition_bytes": [100, 110, 90, 105],
                    "exchange": "file", "output_rows": 50}],
    }
    obs.update(over)
    return obs


# -- quantile sketch ---------------------------------------------------------

def test_sketch_quantiles_and_extremes():
    sk = statstore.sketch_new()
    statstore.sketch_add(sk, [float(i) for i in range(1, 101)], budget=32)
    assert sk["count"] == 100
    assert statstore.sketch_quantile(sk, 0.0) == 1.0  # exact min
    assert statstore.sketch_quantile(sk, 1.0) == 100.0  # exact max
    p50 = statstore.sketch_quantile(sk, 0.5)
    assert 45.0 <= p50 <= 56.0  # bounded error under compression
    assert statstore.sketch_spread(sk) == pytest.approx(80.0, abs=8.0)


def test_sketch_compression_is_deterministic():
    vals = [float((i * 37) % 101) for i in range(200)]
    a, b = statstore.sketch_new(), statstore.sketch_new()
    statstore.sketch_add(a, vals, budget=16)
    statstore.sketch_add(b, vals, budget=16)
    assert a == b  # same input -> byte-identical sketch


def test_sketch_merge_preserves_count_and_extremes():
    a, b = statstore.sketch_new(), statstore.sketch_new()
    statstore.sketch_add(a, [1.0, 2.0, 3.0], budget=8)
    statstore.sketch_add(b, [100.0], budget=8)
    m = statstore.sketch_merge(a, b, budget=8)
    assert m["count"] == 4
    assert m["min"] == 1.0 and m["max"] == 100.0
    assert statstore.sketch_quantile(m, 1.0) == 100.0


def test_empty_sketch_quantile_is_none():
    assert statstore.sketch_quantile(statstore.sketch_new(), 0.5) is None
    assert statstore.sketch_spread(statstore.sketch_new()) is None


# -- disabled path -----------------------------------------------------------

def test_disabled_by_default_writes_nothing(tmp_path):
    d = str(tmp_path / "stats")
    config.conf.set(config.STATS_DIR.key, d)  # dir set, enable NOT set
    statstore.reset_conf_probe()
    assert statstore.enabled() is False
    assert statstore.ingest(_obs()) is None
    assert statstore.prior("fp-a") is None
    assert not os.path.exists(d)  # not even the directory


# -- merge across runs -------------------------------------------------------

def test_two_runs_merge_into_one_record(stats_on):
    statstore.ingest(_obs(wall=1.0))
    rec = statstore.ingest(_obs(wall=1.2))
    assert rec["run_count"] == 2
    assert rec["wall_s"]["count"] == 2
    # counters accumulate; ratios are recomputed from the tallies
    assert rec["counters"]["partial_agg_probe_rows"] == 200
    assert rec["derived"]["agg_probe_ratio"] == pytest.approx(0.4)
    assert rec["derived"]["expr_cache_hit_rate"] == pytest.approx(0.75)
    assert rec["derived"]["wall_p50_s"] == pytest.approx(1.1)
    # the stage merged under its subplan fingerprint
    st = rec["stages"]["st-0"]
    assert st["run_count"] == 2
    assert st["partition_bytes"]["count"] == 8
    assert st["last_partition_bytes"] == [100, 110, 90, 105]


def test_more_runs_tighten_the_wall_spread(stats_on):
    statstore.ingest(_obs(wall=1.0))
    statstore.ingest(_obs(wall=5.0))
    wide = statstore.prior("fp-a")["derived"]["wall_spread_s"]
    for _ in range(20):
        statstore.ingest(_obs(wall=3.0))
    tight = statstore.prior("fp-a")["derived"]["wall_spread_s"]
    assert tight < wide  # p90-p10 narrows as mass concentrates


def test_fresh_process_replay_is_bit_stable(stats_on):
    statstore.ingest(_obs(wall=1.0))
    rec = statstore.ingest(_obs(wall=1.5))
    in_proc = statstore._dumps(rec)
    out = subprocess.run(
        [sys.executable, "-c",
         "import json,sys\n"
         "from blaze_tpu.plan import statstore\n"
         "r = statstore.StatStore(sys.argv[1]).record('fp-a')\n"
         "sys.stdout.write(statstore._dumps(r))", stats_on],
        capture_output=True, text=True, check=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.stdout == in_proc


def test_torn_trailing_line_is_skipped(stats_on):
    statstore.ingest(_obs(wall=1.0))
    path = statstore._fp_path(stats_on, "fp-a")
    with open(path, "a") as f:
        f.write('{"v": 1, "run_cou')  # crash mid-append
    rec = statstore.StatStore(stats_on).record("fp-a")
    assert rec is not None and rec["run_count"] == 1
    # the next ingest merges onto the last VALID line
    rec = statstore.ingest(_obs(wall=2.0))
    assert rec["run_count"] == 2


def test_compaction_bounds_file_growth(stats_on):
    for i in range(statstore._MAX_LINES + 3):
        statstore.ingest(_obs(wall=1.0 + i * 0.01))
    path = statstore._fp_path(stats_on, "fp-a")
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) <= statstore._MAX_LINES
    rec = statstore.StatStore(stats_on).record("fp-a")
    assert rec["run_count"] == statstore._MAX_LINES + 3  # nothing lost


def test_retention_prunes_oldest_fingerprints(stats_on):
    config.conf.set(config.STATS_MAX_FINGERPRINTS.key, 3)
    for i in range(6):
        path = statstore._fp_path(stats_on, f"fp-{i}")
        statstore.ingest(_obs(fp=f"fp-{i}"))
        os.utime(path, (1_000_000 + i, 1_000_000 + i))
    statstore.ingest(_obs(fp="fp-9"))
    fps = statstore.StatStore(stats_on).fingerprints()
    assert len(fps) <= 3
    assert "fp-9" in fps  # newest survives


def test_store_summary_shape(stats_on):
    statstore.ingest(_obs())
    (s,) = statstore.StatStore(stats_on).summary()
    assert s["fingerprint"] == "fp-a"
    assert s["run_count"] == 1
    assert s["stages"] == 1
    assert s["wall_p50_s"] == pytest.approx(1.0)


def test_ingest_counters_exist_in_xla_stats():
    from blaze_tpu.bridge import xla_stats
    snap = xla_stats.snapshot()
    missing = [k for k in statstore.INGEST_COUNTERS if k not in snap]
    assert not missing, f"statstore names unknown counters: {missing}"


# -- advisor -----------------------------------------------------------------

def _record(**runs):
    rec = statstore._new_record("fp-adv")
    for obs in runs.get("observations", [_obs(fp="fp-adv")]):
        statstore.merge_observation(rec, obs)
    return rec


def test_advisor_broadcast_candidate():
    rec = _record()
    kinds = {f["kind"] for f in advisor.findings(rec)}
    assert "broadcast_candidate" in kinds  # ~400B shuffle


def test_advisor_skew_partition_names_the_partition():
    obs = _obs(fp="fp-adv")
    obs["stages"][0]["partition_bytes"] = [100, 100, 100, 5000]
    rec = _record(observations=[obs])
    (f,) = [f for f in advisor.findings(rec)
            if f["kind"] == "skew_partition"]
    assert f["evidence"]["partition"] == 3
    assert f["evidence"]["ratio"] == pytest.approx(50.0)


def test_advisor_host_eviction_and_high_cardinality():
    obs = _obs(fp="fp-adv")
    obs["counters"]["partial_agg_probe_groups"] = 95
    obs["fallback_reasons"] = {"stage_loop": 3}
    rec = _record(observations=[obs])
    kinds = {f["kind"] for f in advisor.findings(rec)}
    assert "high_cardinality_agg" in kinds  # ratio 0.95 >= 0.8
    assert "host_eviction" in kinds


def test_advisor_low_cache_hit_rate():
    obs = _obs(fp="fp-adv")
    obs["counters"]["expr_programs_built"] = 20
    obs["counters"]["expr_program_cache_hits"] = 2
    rec = _record(observations=[obs])
    assert any(f["kind"] == "low_cache_hit_rate"
               for f in advisor.findings(rec))


def test_advisor_dominant_bottleneck_uses_report():
    rec = statstore._new_record("fp-adv")
    bn = {"dominant": "exchange_wire", "dominant_fraction": 0.7,
          "wall_s": 2.0, "categories": {"exchange_wire": 1.4}}
    (f,) = [f for f in advisor.findings(rec, bn)
            if f["kind"] == "dominant_bottleneck"]
    assert "exchange_wire" in f["summary"]


def test_advisor_findings_are_deterministically_ordered():
    rec = _record()
    a = advisor.findings(rec)
    b = advisor.findings(rec)
    assert a == b
    assert a == sorted(a, key=lambda f: (
        f["kind"], -1 if f["stage"] is None else f["stage"],
        f["summary"]))


def test_advisor_empty_record_is_quiet():
    assert advisor.findings(None) == []
    assert advisor.findings(statstore._new_record("fp-x")) == []


# -- end-to-end: scheduler ingest -------------------------------------------

def test_scheduler_ingests_boundaries_and_merges_priors(
        stats_on, tmp_path):
    from blaze_tpu.memory import MemManager
    from blaze_tpu.plan.stages import DagScheduler
    from tests.test_serving import _two_stage_plan

    MemManager.init(4 << 30)
    plan = _two_stage_plan(tmp_path, n=2_000)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        fp = None
        for i in range(2):
            sched = DagScheduler(work_dir=str(tmp_path / f"run{i}"))
            sched.run_collect(plan)
            assert sched.stats_fingerprint
            assert fp in (None, sched.stats_fingerprint)  # stable fp
            fp = sched.stats_fingerprint
        rec = statstore.prior(fp)
        assert rec["run_count"] == 2
        assert rec["wall_s"]["count"] == 2
        # the shuffle boundary was captured with real partition bytes
        assert rec["stages"], "no stage boundary ingested"
        st = next(iter(rec["stages"].values()))
        assert st["run_count"] == 2
        assert sum(st["last_partition_bytes"]) > 0
        # and the merged record replays bit-stable from disk
        again = statstore.StatStore(stats_on).record(fp)
        assert statstore._dumps(again) == statstore._dumps(rec)
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


# -- knobs documented --------------------------------------------------------

def test_stats_knobs_are_documented():
    docs = config.generate_docs()
    for opt in (config.STATS_ENABLE, config.STATS_DIR,
                config.STATS_MAX_FINGERPRINTS,
                config.STATS_SKETCH_CENTROIDS,
                config.STATS_ADVISOR_BROADCAST_BYTES,
                config.STATS_ADVISOR_SKEW_FACTOR):
        assert opt.key in docs
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "docs", "configuration.md")) as f:
        committed = f.read()
    assert config.STATS_ENABLE.key in committed, \
        "docs/configuration.md is stale: regenerate via " \
        "config.generate_docs()"
