"""Dictionary-encoded strings on the device lanes (ISSUE 20): utf8
columns ride the int lanes as int32 codes — scan-side stream encoding,
dict-keyed group-bys through the device-resident stage loop, equality /
IN-list predicates on codes, cross-batch dictionary unification — all
bit-identical to the plain utf8 host lane, with lossless degradation on
dictionary overflow and injected faults.  Knob off = byte-identical
seed behaviour."""

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.batch import ColumnBatch, DictColumn
from blaze_tpu.bridge import xla_stats
from blaze_tpu.cache import reset_cache
from blaze_tpu.memory import MemManager
from blaze_tpu.plan.stages import DagScheduler

# the hostile key domain every sweep draws from: empty string, repeated
# keys, multi-byte utf8 (2-, 3- and 4-byte sequences), and NULLs mixed
# in by the callers
HOSTILE = ["", "a", "aa", "véhicule", "北京市", "zäh-🚀", "ключ",
           "nul\x00byte", " lead", "trail "]


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    reset_cache()
    try:
        yield
    finally:
        faults.clear()
        reset_cache()


@pytest.fixture
def dict_on():
    config.conf.set(config.ENCODING_DICT_ENABLE.key, True)
    try:
        yield
    finally:
        config.conf.unset(config.ENCODING_DICT_ENABLE.key)


@pytest.fixture
def loop_on():
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")
    try:
        yield
    finally:
        config.conf.unset(config.STAGE_DEVICE_LOOP_ENABLE.key)


@pytest.fixture
def staged_path():
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


def _utf8_table(n=4000, n_keys=40, seed=5, null_rate=0.06):
    rng = np.random.default_rng(seed)
    domain = HOSTILE + [f"sku-{i:04d}" for i in range(n_keys)]
    keys = [domain[i] if rng.random() > null_rate else None
            for i in rng.integers(0, len(domain), n)]
    return pa.table({"k": pa.array(keys, type=pa.string()),
                     "v": pa.array(rng.random(n))})


_UTF8_SCHEMA = {"fields": [
    {"name": "k", "type": {"id": "utf8"}, "nullable": True},
    {"name": "v", "type": {"id": "float64"}, "nullable": True}]}


def _group_by_plan(tmp_path, t, tag="", n_reduce=3):
    paths = []
    half = t.num_rows // 2
    for i in range(2):
        p = str(tmp_path / f"in{tag}-{i}.parquet")
        pq.write_table(t.slice(i * half, half), p)
        paths.append(p)
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]},
                 {"fn": "count", "mode": "final", "name": "c",
                  "args": [{"kind": "column", "index": 2}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]},
                         {"fn": "count", "mode": "partial", "name": "c",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan",
                          "schema": _UTF8_SCHEMA,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}


def _sorted_df(tbl):
    return (tbl.to_pandas().sort_values("k", na_position="first")
            .reset_index(drop=True))


# -- scan-side encoding -----------------------------------------------------

def test_scan_decode_parity_hostile_data(tmp_path, dict_on):
    """The device-lane scan stream (execute(), where the encoder lives —
    the Arrow-resident collect path stays plain) round-trips every
    hostile utf8 value and NULL exactly through the dictionary
    encoding."""
    from blaze_tpu.bridge.context import TaskContext, task_scope
    from blaze_tpu.plan.planner import create_plan
    t = _utf8_table(n=1500, seed=9, null_rate=0.15)
    p = str(tmp_path / "scan.parquet")
    pq.write_table(t, p)
    config.conf.set(config.BATCH_SIZE.key, 256)
    try:
        pl = create_plan({"kind": "parquet_scan", "schema": _UTF8_SCHEMA,
                          "file_groups": [[p]]})
        before = xla_stats.encoding_stats()
        with task_scope(TaskContext(stage_id=0, partition_id=0)):
            batches = list(pl.execute(0))
    finally:
        config.conf.unset(config.BATCH_SIZE.key)
    after = xla_stats.encoding_stats()
    assert after["dict_encoded_columns"] > before["dict_encoded_columns"]
    assert any(isinstance(cb.columns[0], DictColumn) for cb in batches)
    got = pa.Table.from_batches([cb.to_arrow() for cb in batches])
    assert got.column("k").combine_chunks().equals(
        t.column("k").combine_chunks())
    assert got.column("v").combine_chunks().equals(
        t.column("v").combine_chunks())


def test_disabled_path_is_plain(tmp_path):
    """Knob off (the default): no column is dict-encoded anywhere and
    the encoding counters stay zero — byte-identical seed behaviour."""
    t = _utf8_table(n=500)
    before = xla_stats.encoding_stats()
    cb = ColumnBatch.from_arrow(t)
    for c in cb.columns:
        assert not isinstance(c, DictColumn)
    assert xla_stats.encoding_stats() == before


def test_stream_encoder_prefix_growth():
    """The per-stream encoder only ever APPENDS to its dictionary, so
    the last snapshot decodes every earlier batch's codes (the property
    the stage loop's drain depends on)."""
    from blaze_tpu.ops.scan import _StreamDictEncoder
    from blaze_tpu.plan.types import schema_from_dict
    schema = schema_from_dict(_UTF8_SCHEMA)
    enc = _StreamDictEncoder(schema, max_entries=1 << 16)
    t = _utf8_table(n=3000, seed=13)
    dicts = []
    for rb in t.to_batches(max_chunksize=256):
        out = enc(rb)
        assert pa.types.is_dictionary(out.column(0).type)
        dicts.append(out.column(0).dictionary)
        # decode parity per batch
        assert out.column(0).cast(pa.string()).equals(
            rb.column(0).cast(pa.string()))
    for a, b in zip(dicts, dicts[1:]):
        assert b.slice(0, len(a)).equals(a)  # prefix property


# -- group-by through the stage loop ----------------------------------------

def test_string_group_by_rides_stage_loop(tmp_path, staged_path,
                                          loop_on, dict_on):
    t = _utf8_table(n=6000)
    plan = _group_by_plan(tmp_path, t)
    config.conf.set(config.ENCODING_DICT_ENABLE.key, False)
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "off")
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-off")).run_collect(plan))
    config.conf.set(config.ENCODING_DICT_ENABLE.key, True)
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")

    before = xla_stats.snapshot()
    got = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-on")).run_collect(plan))
    d = xla_stats.delta(before)
    assert got.equals(clean)  # bit-identical, not approximately
    assert d["stage_loop_tasks"] >= 2  # both map tasks folded on codes
    assert d["stage_loop_fallbacks"] == 0
    assert d["dict_encoded_columns"] >= 1


def test_string_keys_without_dict_still_evict(tmp_path, staged_path,
                                              loop_on):
    """Knob off: utf8 group keys keep rejecting the loop, and the
    rejection is accounted as a STRING eviction (satellite 2)."""
    plan = _group_by_plan(tmp_path, _utf8_table(n=2000), tag="ev")
    before = xla_stats.snapshot()
    DagScheduler(work_dir=str(tmp_path / "dag")).run_collect(plan)
    d = xla_stats.delta(before)
    assert d["stage_loop_tasks"] == 0
    assert d["host_evictions_string"] >= 1


def test_dictionary_overflow_falls_back_lossless(tmp_path, staged_path,
                                                 loop_on, dict_on):
    """More distinct keys than maxEntries: the stream encoder retires
    the column mid-stream, the loop's guard falls back WHOLESALE, and
    the result is still exact."""
    rng = np.random.default_rng(3)
    n = 4000
    keys = [f"key-{i:05d}" for i in rng.integers(0, 500, n)]
    t = pa.table({"k": pa.array(keys, type=pa.string()),
                  "v": pa.array(rng.random(n))})
    plan = _group_by_plan(tmp_path, t, tag="ovf")
    config.conf.set(config.ENCODING_DICT_ENABLE.key, False)
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "off")
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-c")).run_collect(plan))
    config.conf.set(config.ENCODING_DICT_ENABLE.key, True)
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")
    config.conf.set(config.ENCODING_DICT_MAX_ENTRIES.key, 64)
    config.conf.set(config.BATCH_SIZE.key, 256)
    try:
        before = xla_stats.snapshot()
        got = _sorted_df(DagScheduler(
            work_dir=str(tmp_path / "dag-o")).run_collect(plan))
        d = xla_stats.delta(before)
    finally:
        config.conf.unset(config.ENCODING_DICT_MAX_ENTRIES.key)
        config.conf.unset(config.BATCH_SIZE.key)
    assert got.equals(clean)
    assert d["stage_loop_fallbacks"] >= 1


def test_injected_fault_mid_stream_falls_back(tmp_path, staged_path,
                                              loop_on, dict_on):
    plan = _group_by_plan(tmp_path, _utf8_table(n=4000), tag="flt")
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "off")
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-c")).run_collect(plan))
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")
    before = xla_stats.snapshot()
    with faults.scoped(("device-loop", dict(p=1.0))):
        got = _sorted_df(DagScheduler(
            work_dir=str(tmp_path / "dag-f")).run_collect(plan))
    d = xla_stats.delta(before)
    assert got.equals(clean)
    assert d["stage_loop_fallbacks"] >= 1
    assert d["stage_loop_tasks"] == 0


# -- predicates on codes ----------------------------------------------------

def _dict_batch(values, extra=None):
    arrs = {"k": pc.dictionary_encode(pa.array(values, type=pa.string()))}
    if extra is not None:
        arrs["v"] = extra
    return ColumnBatch.from_arrow(pa.table(arrs))


def _plain_batch(values, extra=None):
    arrs = {"k": pa.array(values, type=pa.string())}
    if extra is not None:
        arrs["v"] = extra
    return ColumnBatch.from_arrow(pa.table(arrs))


@pytest.mark.parametrize("needle", ["véhicule", "", "absent-key"])
def test_equality_on_codes_matches_host(needle):
    from blaze_tpu.exprs.base import Literal, col
    from blaze_tpu.exprs.binary import BinaryExpr
    from blaze_tpu.schema import UTF8
    vals = HOSTILE * 3 + [None, None]
    e = BinaryExpr("==", col(0), Literal(needle, UTF8))
    got = e.evaluate(_dict_batch(vals))
    want = e.evaluate(_plain_batch(vals))
    n = len(vals)
    assert got.to_host(n).equals(want.to_host(n))


def test_in_list_on_codes_matches_host():
    from blaze_tpu.exprs.base import col
    from blaze_tpu.exprs.conditional import InList
    vals = HOSTILE * 3 + [None]
    for members in (("véhicule", "北京市", "missing"),
                    ("a", None), ("nope",)):
        for negated in (False, True):
            e = InList(col(0), tuple(members), negated)
            got = e.evaluate(_dict_batch(vals))
            want = e.evaluate(_plain_batch(vals))
            n = len(vals)
            assert got.to_host(n).equals(want.to_host(n)), \
                (members, negated)


def test_dict_vs_dict_equality_across_dictionaries():
    """Two dict columns with DIFFERENT dictionaries must not compare
    raw codes."""
    from blaze_tpu.exprs.base import col
    from blaze_tpu.exprs.binary import BinaryExpr
    a = pa.array(["x", "y", "z", "x", None], type=pa.string())
    b = pa.array(["z", "y", "x", "x", "x"], type=pa.string())
    t_dict = pa.table({"a": pc.dictionary_encode(a),
                       "b": pc.dictionary_encode(b)})
    t_plain = pa.table({"a": a, "b": b})
    e = BinaryExpr("==", col(0), col(1))
    got = e.evaluate(ColumnBatch.from_arrow(t_dict))
    want = e.evaluate(ColumnBatch.from_arrow(t_plain))
    assert got.to_host(5).equals(want.to_host(5))


# -- concat / dictionary unification ----------------------------------------

def test_concat_unifies_disjoint_dictionaries():
    """Batches whose dictionaries DON'T share a prefix merge through the
    remap path, counted in dict_exchange_remaps."""
    t1 = pa.table({"k": pc.dictionary_encode(
        pa.array(["a", "b", "a"], type=pa.string()))})
    t2 = pa.table({"k": pc.dictionary_encode(
        pa.array(["c", "b", None, "d"], type=pa.string()))})
    b1 = ColumnBatch.from_arrow(t1)
    b2 = ColumnBatch.from_arrow(t2)
    assert isinstance(b1.columns[0], DictColumn)
    before = xla_stats.encoding_stats()["dict_exchange_remaps"]
    out = ColumnBatch.concat([b1, b2])
    assert xla_stats.encoding_stats()["dict_exchange_remaps"] > before
    got = out.to_arrow().column(0)
    assert got.cast(pa.string()).to_pylist() == \
        ["a", "b", "a", "c", "b", None, "d"]


# -- hash parity ------------------------------------------------------------

def test_decoded_codes_hash_like_raw_strings():
    """The file-exchange wire decodes codes back to utf8 before
    hashing; the decode must reproduce the exact bytes, so partition
    ids are unchanged by the encoding."""
    from blaze_tpu.kernels import hashing as H
    vals = (HOSTILE * 7)[:64] + [None] * 3
    arr = pa.array(vals, type=pa.string())
    enc = pc.dictionary_encode(arr)
    cb = ColumnBatch.from_arrow(pa.table({"k": enc}))
    decoded = cb.columns[0].to_arrow(cb.num_rows)

    def pids(a, p):
        (mat, lengths), valid = H.string_column_to_padded_bytes(a)
        return H.spark_partition_ids([((mat, lengths), valid)],
                                     ["utf8"], p, xp=np).tolist()

    for p in (3, 8):
        assert pids(arr, p) == pids(decoded, p)


# -- recompile guard + subplan cache ----------------------------------------

def test_dict_stage_zero_steady_state_recompiles(tmp_path, staged_path,
                                                 loop_on, dict_on):
    """The dict-keyed program fingerprints like any other: the first
    run builds it, every later run (same shape) reuses it with ZERO
    XLA recompiles."""
    plan = _group_by_plan(tmp_path, _utf8_table(n=4000), tag="rc")
    first = xla_stats.snapshot()
    DagScheduler(work_dir=str(tmp_path / "d0")).run_collect(plan)
    d0 = xla_stats.delta(first)
    # built on first-ever sight; an earlier test with the same shape may
    # have built it already, in which case this run is pure cache hits
    assert (d0["stage_loop_programs_built"]
            + d0["stage_loop_program_cache_hits"]) >= 1
    before = xla_stats.snapshot()
    DagScheduler(work_dir=str(tmp_path / "d1")).run_collect(plan)
    d = xla_stats.delta(before)
    assert d["stage_loop_programs_built"] == 0
    assert d["total_compiles"] == 0, \
        f"steady-state recompiles: {d['total_compiles']}"


def test_encoding_knobs_ride_program_keys(tmp_path, staged_path, loop_on):
    """Flipping the dict knob must select a DIFFERENT program (the
    fingerprint carries the encoding), never silently reuse one traced
    for the other representation."""
    from blaze_tpu.plan import stage_compiler
    from blaze_tpu.plan.column_pruning import prune_columns
    from blaze_tpu.plan.fused import fuse_plan
    from blaze_tpu.plan.planner import collapse_filter_project, create_plan
    t = _utf8_table(n=500)
    p = str(tmp_path / "fp.parquet")
    pq.write_table(t, p)
    plan = {"kind": "hash_agg",
            "groupings": [{"expr": {"kind": "column", "index": 0},
                           "name": "k"}],
            "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                      "args": [{"kind": "column", "index": 1}]}],
            "input": {"kind": "parquet_scan", "schema": _UTF8_SCHEMA,
                      "file_groups": [[p]]}}

    def compile_under(dict_enable):
        config.conf.set(config.ENCODING_DICT_ENABLE.key, dict_enable)
        try:
            agg = fuse_plan(prune_columns(collapse_filter_project(
                create_plan(plan))))
            return stage_compiler.try_compile(agg)
        finally:
            config.conf.unset(config.ENCODING_DICT_ENABLE.key)

    off = compile_under(False)
    on = compile_under(True)
    assert off is None  # utf8 keys are loop-ineligible without codes
    assert on is not None
    assert any(s is not None for s in on.dict_keys)


def test_subplan_cache_hits_dict_stage(tmp_path, staged_path, dict_on,
                                       loop_on):
    config.conf.set(config.CACHE_ENABLE.key, True)
    try:
        plan = _group_by_plan(tmp_path, _utf8_table(n=3000), tag="sc")
        before = xla_stats.cache_stats()
        r1 = DagScheduler(work_dir=str(tmp_path / "c0")).run_collect(plan)
        d1 = {k: xla_stats.cache_stats()[k] - before[k] for k in before}
        assert d1.get("subplan_cache_puts", 0) >= 1
        before = xla_stats.cache_stats()
        r2 = DagScheduler(work_dir=str(tmp_path / "c1")).run_collect(plan)
        d2 = {k: xla_stats.cache_stats()[k] - before[k] for k in before}
        assert d2.get("subplan_cache_hits", 0) >= 1
        assert _sorted_df(r2).equals(_sorted_df(r1))
    finally:
        config.conf.unset(config.CACHE_ENABLE.key)


# -- explain footer ---------------------------------------------------------

def test_explain_encodings_footer(tmp_path, staged_path, loop_on, dict_on):
    from blaze_tpu.plan.explain import format_encodings_footer
    plan = _group_by_plan(tmp_path, _utf8_table(n=1500), tag="xp")
    before = xla_stats.snapshot()
    DagScheduler(work_dir=str(tmp_path / "d")).run_collect(plan)
    footer = format_encodings_footer(xla_stats.delta(before))
    assert footer and "encodings:" in footer
    assert "dict_cols=" in footer
