"""Planner tests: IR dict -> operator tree -> results, incl. a TPC-DS
q01-shaped two-stage plan through JSON round-trip (the TaskDefinition
decode path, ref rt.rs:79-90)."""

import datetime

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import schema as S
from blaze_tpu.bridge.resource import put_resource
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import (create_plan, plan_from_json, plan_to_json,
                            schema_to_dict)


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def T(**kw):
    return pa.table(kw)


def _i64(): return {"id": "int64"}
def _f64(): return {"id": "float64"}
def _col(i): return {"kind": "column", "index": i}
def _lit(v, t=None):
    if t is None:
        t = _i64() if isinstance(v, int) else _f64()
    return {"kind": "literal", "value": v, "type": t}


def test_filter_project_plan_json_roundtrip():
    t = T(a=pa.array(range(100)), b=pa.array(np.arange(100) * 1.5))
    put_resource("tbl1", t)
    plan_ir = {
        "kind": "project",
        "names": ["a2", "b"],
        "exprs": [{"kind": "binary", "op": "*", "l": _col(0),
                   "r": _lit(2)}, _col(1)],
        "input": {
            "kind": "filter",
            "predicates": [{"kind": "binary", "op": ">", "l": _col(0),
                            "r": _lit(89)}],
            "input": {"kind": "memory_scan", "resource_id": "tbl1",
                      "schema": schema_to_dict(S.Schema.from_arrow(t.schema))},
        },
    }
    plan = create_plan(plan_from_json(plan_to_json(plan_ir)))
    got = plan.execute_collect().to_arrow()
    assert got.column("a2").to_pylist() == [x * 2 for x in range(90, 100)]


def test_agg_plan():
    t = T(k=pa.array([1, 1, 2, 2, 2]), v=pa.array([1., 2., 3., 4., 5.]))
    put_resource("tbl2", t)
    ir = {
        "kind": "hash_agg",
        "groupings": [{"expr": _col(0), "name": "k"}],
        "aggs": [{"fn": "sum", "args": [_col(1)], "mode": "complete",
                  "name": "s"},
                 {"fn": "count", "args": [_col(1)], "mode": "complete",
                  "name": "c"}],
        "input": {"kind": "memory_scan", "resource_id": "tbl2",
                  "schema": schema_to_dict(S.Schema.from_arrow(t.schema))},
    }
    got = create_plan(ir).execute_collect().to_arrow()
    d = dict(zip(got.column("k").to_pylist(), got.column("s").to_pylist()))
    assert d == {1: 3.0, 2: 12.0}


def test_join_sort_limit_plan():
    l = T(k=pa.array([1, 2, 3]), a=pa.array(["x", "y", "z"]))
    r = T(k=pa.array([2, 3, 4]), b=pa.array([20.0, 30.0, 40.0]))
    put_resource("L", l)
    put_resource("R", r)
    def scan(rid, t):
        return {"kind": "memory_scan", "resource_id": rid,
                "schema": schema_to_dict(S.Schema.from_arrow(t.schema))}
    ir = {
        "kind": "limit", "limit": 1,
        "input": {
            "kind": "sort",
            "specs": [{"expr": _col(3), "descending": True}],
            "input": {
                "kind": "sort_merge_join", "join_type": "inner",
                "left": scan("L", l), "right": scan("R", r),
                "left_keys": [_col(0)], "right_keys": [_col(0)],
            },
        },
    }
    got = create_plan(ir).execute_collect().to_arrow()
    assert got.num_rows == 1
    assert got.column("b").to_pylist() == [30.0]
    assert got.column("a").to_pylist() == ["z"]


def test_scalar_function_and_case_plan():
    t = T(s=pa.array(["ab", "cdef", None]))
    put_resource("S1", t)
    ir = {
        "kind": "project", "names": ["n", "tag"],
        "exprs": [
            {"kind": "scalar_function", "name": "length", "args": [_col(0)]},
            {"kind": "case",
             "branches": [[{"kind": "is_null", "child": _col(0)},
                           _lit("none", {"id": "utf8"})]],
             "else": _col(0)},
        ],
        "input": {"kind": "memory_scan", "resource_id": "S1",
                  "schema": schema_to_dict(S.Schema.from_arrow(t.schema))},
    }
    got = create_plan(ir).execute_collect().to_arrow()
    assert got.column("n").to_pylist() == [2, 4, None]
    assert got.column("tag").to_pylist() == ["ab", "cdef", "none"]


def test_q01_shaped_two_stage_plan(tmp_path):
    """TPC-DS q01 shape: parquet scan -> filter -> partial agg ->
    hash exchange -> final agg -> sort -> limit (BASELINE config #1)."""
    rng = np.random.default_rng(0)
    n = 20000
    t = pa.table({
        "sr_customer_sk": pa.array(rng.integers(1, 1000, n)),
        "sr_store_sk": pa.array(rng.integers(1, 10, n)),
        "sr_return_amt": pa.array(np.round(rng.random(n) * 100, 2)),
        "sr_returned_date_sk": pa.array(rng.integers(2450000, 2451000, n)),
    })
    path = str(tmp_path / "store_returns.parquet")
    pq.write_table(t, path, row_group_size=4096)
    schema_d = schema_to_dict(S.Schema.from_arrow(t.schema))
    ir = {
        "kind": "sort",
        "specs": [{"expr": _col(2), "descending": True}],
        "fetch": 10,
        "input": {
          # global top-K needs a single-partition exchange (Spark's
          # TakeOrderedAndProject plans the same collapse)
          "kind": "local_exchange",
          "partitioning": {"kind": "single"},
          "input": {
            "kind": "hash_agg",
            "groupings": [{"expr": _col(0), "name": "customer"},
                          {"expr": _col(1), "name": "store"}],
            "aggs": [{"fn": "sum", "args": [_col(2)],
                      "mode": "partial_merge", "name": "total"}],
            "input": {
                "kind": "local_exchange",
                "partitioning": {"kind": "hash", "num_partitions": 3,
                                 "exprs": [_col(0), _col(1)]},
                "input": {
                    "kind": "hash_agg",
                    "groupings": [{"expr": _col(0), "name": "customer"},
                                  {"expr": _col(1), "name": "store"}],
                    "aggs": [{"fn": "sum", "args": [_col(2)],
                              "mode": "partial", "name": "total"}],
                    "input": {
                        "kind": "filter",
                        "predicates": [{"kind": "binary", "op": ">",
                                        "l": _col(3), "r": _lit(2450500)}],
                        "input": {"kind": "parquet_scan",
                                  "schema": schema_d,
                                  "file_groups": [[path]],
                                  "predicate": {
                                      "kind": "binary", "op": ">",
                                      "l": {"kind": "column", "index": 3,
                                            "name": "sr_returned_date_sk"},
                                      "r": _lit(2450500)}},
                    },
                },
            },
          },
        },
    }
    plan = create_plan(ir)
    got = plan.execute_collect().to_arrow()
    # host oracle
    df = t.to_pandas()
    df = df[df.sr_returned_date_sk > 2450500]
    want = (df.groupby(["sr_customer_sk", "sr_store_sk"])
            .sr_return_amt.sum().sort_values(ascending=False)[:10])
    assert got.num_rows == 10
    assert np.allclose(np.sort(got.column("total.sum").to_numpy()),
                       np.sort(want.to_numpy()))


def test_window_and_generate_plan():
    t = T(g=pa.array([1, 1, 2]), v=pa.array([3, 1, 5]),
          xs=pa.array([[1, 2], [3], []], type=pa.list_(pa.int64())))
    put_resource("W1", t)
    scan = {"kind": "memory_scan", "resource_id": "W1",
            "schema": schema_to_dict(S.Schema.from_arrow(t.schema))}
    ir = {
        "kind": "window",
        "functions": [{"kind": "row_number", "name": "rn"}],
        "partition_by": [_col(0)],
        "order_by": [{"expr": _col(1)}],
        "input": {"kind": "sort",
                  "specs": [{"expr": _col(0)}, {"expr": _col(1)}],
                  "input": scan},
    }
    got = create_plan(ir).execute_collect().to_arrow()
    assert got.column("rn").to_pylist() == [1, 2, 1]
    ir2 = {
        "kind": "generate", "required_cols": [0],
        "generator": {"kind": "explode", "child": _col(2)},
        "input": scan,
    }
    got2 = create_plan(ir2).execute_collect().to_arrow()
    assert got2.column("col").to_pylist() == [1, 2, 3]


def test_parquet_sink_plan(tmp_path):
    t = T(a=pa.array([1, 2, 3]))
    put_resource("K1", t)
    out = str(tmp_path / "out")
    ir = {"kind": "parquet_sink", "path": out,
          "input": {"kind": "memory_scan", "resource_id": "K1",
                    "schema": schema_to_dict(S.Schema.from_arrow(t.schema))}}
    plan = create_plan(ir)
    list(plan.execute(0))
    back = pq.read_table(out)
    assert back.column("a").to_pylist() == [1, 2, 3]
