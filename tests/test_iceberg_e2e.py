"""End-to-end Iceberg scan itest: partition + row-group pruning
(ops/pruning.py) composed with IcebergDeleteFilter position/equality
deletes, with a divergence check against the unpruned plan — the
lakehouse leg of ROADMAP item 4 (connectors/ exercised as a real query
leg, not dead code)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import blaze_tpu.connectors  # noqa: F401  (registers providers)
from blaze_tpu.connectors.provider import build_scan
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import FilterExec
from blaze_tpu.plan.exprs import expr_from_dict
from blaze_tpu.schema import Schema


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


ROWS_PER_FILE = 8192          # 4 row groups of 2048
N_PARTS = 3


def _lit(v):
    return {"kind": "literal", "value": v, "type": {"id": "int64"}}


def _col(i):
    return {"kind": "column", "index": i}


def _table_files(tmp_path):
    """An iceberg-style partitioned table: one file per partition value
    `p`, each holding a disjoint sorted id range (tight row-group
    stats), with the partition column only in metadata."""
    paths = []
    for p in range(N_PARTS):
        base = p * ROWS_PER_FILE
        t = pa.table({
            "id": pa.array(np.arange(base, base + ROWS_PER_FILE),
                           type=pa.int64()),
            "v": pa.array(np.arange(ROWS_PER_FILE, dtype=np.float64))})
        path = str(tmp_path / f"part-{p}.parquet")
        pq.write_table(t, path, row_group_size=2048)
        paths.append(path)
    return paths


def _collect(plan):
    out = []
    for p in range(plan.num_partitions):
        out.extend(b.compact().to_arrow() for b in plan.execute(p))
    out = [b for b in out if b.num_rows]
    return pa.Table.from_batches(out) if out else None


def test_iceberg_pruned_scan_with_deletes_matches_unpruned(tmp_path):
    paths = _table_files(tmp_path)
    schema = Schema.from_arrow(pa.schema([
        ("id", pa.int64()), ("v", pa.float64()), ("p", pa.int64())]))

    # v2 position deletes against the p=1 file: rows in the FIRST and
    # SECOND row groups (absolute file positions — pruning must not
    # shift them) plus one in a group the predicate prunes away
    pos_deleted = [3, 100, 2500, 7000]
    dp = str(tmp_path / "del.pos.parquet")
    pq.write_table(pa.table({
        "file_path": pa.array([paths[1]] * len(pos_deleted)),
        "pos": pa.array(pos_deleted, type=pa.int64())}), dp)
    # equality deletes by id, also hitting the kept range
    ep = str(tmp_path / "del.eq.parquet")
    eq_deleted = [8192 + 1, 8192 + 2046, 8192 + 2049]
    pq.write_table(pa.table({"id": pa.array(eq_deleted,
                                            type=pa.int64())}), ep)

    desc = {"splits": [
        {"path": paths[p], "partition_values": {"p": p},
         **({"position_deletes": [dp],
             "equality_deletes": [{"path": ep, "equality_ids": ["id"]}]}
            if p == 1 else {})}
        for p in range(N_PARTS)]}

    # WHERE p = 1 AND id < 8192 + 3000  (keeps ~1.5 row groups of one
    # of the three partition files)
    hi = 8192 + 3000
    pred_ir = {"kind": "binary", "op": "and",
               "l": {"kind": "binary", "op": "==",
                     "l": _col(2), "r": _lit(1)},
               "r": {"kind": "binary", "op": "<",
                     "l": _col(0), "r": _lit(hi)}}
    pred = expr_from_dict(pred_ir, schema)

    pruned_scan = build_scan("iceberg", desc, schema, predicate=pred)
    pruned = _collect(FilterExec(pruned_scan, [pred]))

    unpruned_scan = build_scan("iceberg", desc, schema)
    unpruned = _collect(FilterExec(unpruned_scan, [pred]))

    # divergence check: pruning is invisible in the result
    order = [("id", "ascending")]
    assert pruned.sort_by(order).equals(unpruned.sort_by(order))

    # and the pruning actually happened
    v = pruned_scan.metrics.values
    assert v.get("pruned_splits") == 2          # p=0 and p=2 dropped
    assert v.get("pruned_row_groups", 0) >= 2   # id-range groups dropped
    assert unpruned_scan.metrics.values.get("pruned_splits", 0) == 0

    # deletes composed with pruning: the positionally- and
    # equality-deleted ids in the kept range are gone, nothing else
    ids = set(pruned.column("id").to_pylist())
    expect = (set(range(8192, hi))
              - {8192 + 3, 8192 + 100, 8192 + 2500}
              - set(eq_deleted))
    assert ids == expect
    assert pruned.column("p").to_pylist() == [1] * len(ids)


def test_iceberg_partition_prune_to_empty(tmp_path):
    paths = _table_files(tmp_path)
    schema = Schema.from_arrow(pa.schema([
        ("id", pa.int64()), ("p", pa.int64())]))
    desc = {"splits": [{"path": paths[p], "partition_values": {"p": p}}
                       for p in range(N_PARTS)]}
    pred = expr_from_dict(
        {"kind": "binary", "op": "==", "l": _col(1), "r": _lit(99)},
        schema)
    scan = build_scan("iceberg", desc, schema, predicate=pred)
    assert _collect(scan) is None  # every split disproven before IO
    assert scan.metrics.values.get("pruned_splits") == N_PARTS
