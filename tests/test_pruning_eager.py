"""Eager-scan row-group pruning + mask elision + join runtime-filter
scan pruning (ref parquet page filtering conf.rs:43; runtime-filter
pushdown bloom_filter_might_contain.rs)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq
import pytest

from blaze_tpu.plan.planner import create_plan
from blaze_tpu.plan.fused import fuse_plan


SCHEMA = {"fields": [
    {"name": "dt", "type": {"id": "int64"}, "nullable": True},
    {"name": "k", "type": {"id": "int64"}, "nullable": True},
    {"name": "v", "type": {"id": "float64"}, "nullable": True},
]}


def _col(name):
    return {"kind": "column", "name": name}


def _lit(v):
    return {"kind": "literal", "value": v, "type": {"id": "int64"}}


def _write(tmp_path, with_nulls=False, rows=20_000, group=2048):
    rng = np.random.default_rng(3)
    dt = np.sort(rng.integers(0, 1000, rows))
    k = rng.integers(0, 50, rows)
    v = np.round(rng.random(rows), 3)
    cols = {"dt": pa.array(dt), "k": pa.array(k), "v": pa.array(v)}
    if with_nulls:
        m = rng.random(rows) < 0.01
        cols["dt"] = pa.array(np.where(m, None, dt).tolist(),
                              type=pa.int64())
    t = pa.table(cols)
    p = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(t, p, row_group_size=group)
    return t, p


def _agg_plan(path, lo, hi):
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": _col("k"), "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                  "args": [_col("v")]}],
        "input": {"kind": "filter",
                  "predicates": [
                      {"kind": "binary", "op": ">=", "l": _col("dt"),
                       "r": _lit(lo)},
                      {"kind": "binary", "op": "<=", "l": _col("dt"),
                       "r": _lit(hi)}],
                  "input": {"kind": "parquet_scan", "schema": SCHEMA,
                            "file_groups": [[path]]}}}


def _run_sum(plan_dict):
    plan = fuse_plan(create_plan(plan_dict))
    total = {}
    for cb in plan.execute(0):
        rb = cb.compact().to_arrow()
        for kk, ss in zip(rb.column(0).to_pylist(),
                          rb.column(1).to_pylist()):
            total[kk] = total.get(kk, 0.0) + (ss or 0.0)
    return plan, total


def _oracle(t, lo, hi):
    mask = pc.and_(pc.greater_equal(t["dt"], lo),
                   pc.less_equal(t["dt"], hi))
    f = t.filter(mask)
    agg = f.group_by(["k"]).aggregate([("v", "sum")])
    return dict(zip(agg["k"].to_pylist(), agg["v_sum"].to_pylist()))


def test_eager_pruned_read_matches_oracle_and_prunes(tmp_path):
    t, p = _write(tmp_path)
    lo, hi = 300, 600
    plan, got = _run_sum(_agg_plan(p, lo, hi))
    want = _oracle(t, lo, hi)
    assert set(got) == set(want)
    for kk in want:
        assert abs(got[kk] - want[kk]) < 1e-9
    # clustered dt + narrow range => some of the ~10 groups pruned
    pruned = _find_metric(plan, "pruned_row_groups")
    assert pruned and pruned > 0


def test_mask_not_elided_when_nulls_present(tmp_path):
    """Null dt rows must be dropped by the filter even in row groups the
    stats say are fully covered (always-match must refuse when
    null_count > 0)."""
    t, p = _write(tmp_path, with_nulls=True)
    lo, hi = 0, 1000  # covers EVERY non-null row: elision would be
    #                   tempting, but nulls must still drop
    _plan, got = _run_sum(_agg_plan(p, lo, hi))
    want = _oracle(t, lo, hi)
    assert set(got) == set(want)
    for kk in want:
        assert abs(got[kk] - want[kk]) < 1e-9
    # sanity: the oracle really dropped rows (nulls exist)
    assert sum(1 for v in t["dt"].to_pylist() if v is None) > 0


def test_always_match_refuses_float_stats():
    """Parquet float min/max stats ignore NaN; always-match must never
    trust them."""
    from blaze_tpu.exprs.base import BoundReference, Literal
    from blaze_tpu.exprs.binary import BinaryExpr
    from blaze_tpu.ops.pruning import groups_always_match
    from blaze_tpu.schema import Schema

    t = pa.table({"x": pa.array([1.0, float("nan"), 5.0])})
    import io
    buf = io.BytesIO()
    pq.write_table(t, buf)
    md = pq.ParquetFile(io.BytesIO(buf.getvalue())).metadata
    schema = Schema.from_arrow(t.schema)
    pred = BinaryExpr("<=", BoundReference(0, "x"),
                      Literal(1e9, schema[0].data_type))
    assert not groups_always_match(md, schema, pred, [0])


def test_join_runtime_filter_prunes_probe_scan(tmp_path):
    """Build-side [min,max] runtime filter reaches the probe scan as
    row-group pruning; results equal pyarrow's join."""
    rng = np.random.default_rng(5)
    rows = 30_000
    dt = np.sort(rng.integers(0, 1000, rows))
    probe = pa.table({"dt": pa.array(dt),
                      "pv": pa.array(rng.random(rows))})
    pp = os.path.join(str(tmp_path), "probe.parquet")
    pq.write_table(probe, pp, row_group_size=2048)
    build = pa.table({"bk": pa.array(np.arange(450, 475)),
                      "bv": pa.array(np.arange(25, dtype=np.float64))})
    bp = os.path.join(str(tmp_path), "build.parquet")
    pq.write_table(build, bp)

    plan_dict = {
        "kind": "broadcast_join",
        "join_type": "inner",
        "left_keys": [_col("dt")],
        "right_keys": [_col("bk")],
        "left": {"kind": "parquet_scan",
                 "schema": {"fields": [
                     {"name": "dt", "type": {"id": "int64"},
                      "nullable": True},
                     {"name": "pv", "type": {"id": "float64"},
                      "nullable": True}]},
                 "file_groups": [[pp]]},
        "right": {"kind": "parquet_scan",
                  "schema": {"fields": [
                      {"name": "bk", "type": {"id": "int64"},
                       "nullable": True},
                      {"name": "bv", "type": {"id": "float64"},
                       "nullable": True}]},
                  "file_groups": [[bp]]},
        "build_side": "right"}
    plan = fuse_plan(create_plan(plan_dict))
    out_rows = 0
    for cb in plan.execute(0):
        out_rows += cb.compact().to_arrow().num_rows
    want = probe.join(build, keys="dt", right_keys="bk",
                      join_type="inner")
    assert out_rows == want.num_rows
    # the probe scan must have skipped most of its ~15 row groups
    scan = plan.children[0]
    pruned = _find_metric(scan, "pruned_row_groups")
    assert pruned and pruned > 5


def _find_metric(plan, name):
    """Search the plan tree for a metric value."""
    stack = [plan]
    while stack:
        node = stack.pop()
        v = node.metrics.get(name) if hasattr(node, "metrics") else None
        if v:
            return v
        stack.extend(getattr(node, "children", []) or [])
    return None


@pytest.mark.slow
def test_eager_prune_fuzz(tmp_path):
    """Random row-group layouts x random range/equality predicates vs a
    pyarrow oracle: pruning + mask elision must never change results
    (clustered, reversed, constant, and null-heavy key layouts)."""
    rng = np.random.default_rng(11)
    for trial in range(25):
        rows = int(rng.integers(1, 30_000))
        layout = rng.choice(["sorted", "reversed", "random", "constant"])
        dt = rng.integers(0, 500, rows)
        if layout == "sorted":
            dt = np.sort(dt)
        elif layout == "reversed":
            dt = np.sort(dt)[::-1]
        elif layout == "constant":
            dt[:] = int(dt[0]) if rows else 0
        cols = {"dt": pa.array(dt.copy()),
                "k": pa.array(rng.integers(0, 20, rows)),
                "v": pa.array(np.round(rng.random(rows), 3))}
        if rng.random() < 0.5 and rows:
            m = rng.random(rows) < 0.05
            cols["dt"] = pa.array(
                np.where(m, None, dt).tolist(), type=pa.int64())
        t = pa.table(cols)
        p = os.path.join(str(tmp_path), f"f{trial}.parquet")
        pq.write_table(t, p,
                       row_group_size=int(rng.integers(100, 5000)))
        lo = int(rng.integers(-50, 520))
        hi = lo + int(rng.integers(0, 300))
        preds = [{"kind": "binary", "op": ">=", "l": _col("dt"),
                  "r": _lit(lo)},
                 {"kind": "binary", "op": "<=", "l": _col("dt"),
                  "r": _lit(hi)}]
        if rng.random() < 0.3:
            preds = [{"kind": "binary", "op": "==", "l": _col("dt"),
                      "r": _lit(lo)}]
        plan_dict = {
            "kind": "hash_agg",
            "groupings": [{"expr": _col("k"), "name": "k"}],
            "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                      "args": [_col("v")]}],
            "input": {"kind": "filter", "predicates": preds,
                      "input": {"kind": "parquet_scan", "schema": SCHEMA,
                                "file_groups": [[p]]}}}
        _plan, got = _run_sum(plan_dict)
        mask = None
        for pr in preds:
            op = pr["op"]
            val = pr["r"]["value"]
            m = {"==": pc.equal, ">=": pc.greater_equal,
                 "<=": pc.less_equal}[op](t["dt"], val)
            mask = m if mask is None else pc.and_(mask, m)
        f = t.filter(mask)
        agg = f.group_by(["k"]).aggregate([("v", "sum")])
        want = dict(zip(agg["k"].to_pylist(), agg["v_sum"].to_pylist()))
        assert set(got) == set(want), (trial, layout, lo, hi)
        for kk in want:
            assert abs(got[kk] - (want[kk] or 0.0)) < 1e-9, (trial, kk)
