"""Device-resident shuffle (ISSUE 6): the shared Spark-compatible
partition-id definition across host and device lanes, the DeviceExchange
collective runner with its bucket-ladder capacity retry, the planner's
device-exchange eligibility pass, and the staged scheduler's device path
(bit-identical to the file shuffle, with the `shuffle:` explain footer)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax
import jax.numpy as jnp

from blaze_tpu import config, faults
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.bridge import xla_stats
from blaze_tpu.exprs import col
from blaze_tpu.kernels import hashing as H
from blaze_tpu.memory import MemManager
from blaze_tpu.parallel.collective import partition_ids_for_keys
from blaze_tpu.parallel.stage import DeviceExchange, DeviceExchangeError
from blaze_tpu.plan.planner import exchange_device_spec
from blaze_tpu.plan.stages import DagScheduler
from blaze_tpu.shuffle import HashPartitioning

SENT = -(1 << 60)  # stand-in for NULL keys in multiset comparisons


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    try:
        yield
    finally:
        faults.clear()


@pytest.fixture
def staged_device():
    """Force the staged DAG path and the device shuffle lane."""
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)
        config.conf.unset(config.SHUFFLE_DEVICE.key)


# -- satellite 1: ONE hash definition, host and device lanes ----------------

def _alt_nan(dtype):
    """A NaN with a non-canonical bit pattern (payload bit set)."""
    if dtype == np.float64:
        return np.array([0x7FF8000000000001], dtype=np.uint64
                        ).view(np.float64)[0]
    return np.array([0x7FC00001], dtype=np.uint32).view(np.float32)[0]


def _key_case(tid, n=257, seed=11):
    """(data, valid, host_tid) for one key dtype, NULLs included."""
    rng = np.random.default_rng(seed)
    valid = rng.random(n) > 0.15
    if tid in ("int32", "date32"):
        data = rng.integers(np.iinfo(np.int32).min,
                            np.iinfo(np.int32).max, n).astype(np.int32)
    elif tid in ("int64", "timestamp_us"):
        data = rng.integers(np.iinfo(np.int64).min,
                            np.iinfo(np.int64).max, n, dtype=np.int64)
    elif tid in ("float32", "float64"):
        dt = np.float32 if tid == "float32" else np.float64
        data = (rng.random(n) * 2e4 - 1e4).astype(dt)
        # normalization corner cases: +/-0.0 collapse, every NaN bit
        # pattern hashes as the one canonical NaN
        data[:6] = [0.0, -0.0, np.nan, _alt_nan(dt), np.inf, -np.inf]
    elif tid == "bool":
        data = rng.random(n) > 0.5
    else:  # pragma: no cover
        raise AssertionError(tid)
    return data, valid, tid


@pytest.mark.parametrize("tid", ["bool", "int32", "int64", "float32",
                                 "float64", "date32", "timestamp_us"])
def test_partition_ids_host_device_bitwise_agree(tid):
    """The property behind the device exchange's correctness: the host
    file-shuffle lane (numpy) and the device collective lane (jit'd
    jnp, post arrow->flat re-tagging: date32 rides int32, timestamp_us
    rides int64) put every row in the same reduce partition."""
    data, valid, _ = _key_case(tid)
    for p in (3, 8):
        host = H.spark_partition_ids([(data, valid)], [tid], p, xp=np)
        dev = np.asarray(partition_ids_for_keys(
            [(jnp.asarray(data), jnp.asarray(valid))], p))
        assert host.tolist() == dev.tolist()


def test_partition_ids_match_hash_partitioning_lane():
    """...and both agree with the full HashPartitioning expression lane
    that the file shuffle writer actually runs."""
    data, valid, _ = _key_case("int64")
    t = pa.table({"k": pa.array(data, mask=~valid, type=pa.int64())})
    hp = HashPartitioning([col(0)], 5)
    ids = hp.partition_ids(ColumnBatch.from_arrow(t))
    want = H.spark_partition_ids([(data, valid)], ["int64"], 5, xp=np)
    assert np.asarray(ids)[:len(data)].tolist() == want.tolist()


# -- DeviceExchange unit ----------------------------------------------------

def _kv_columns(n=5000, seed=3, null_rate=0.1):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 200, n, dtype=np.int64)
    kv = rng.random(n) > null_rate
    v = rng.random(n)
    return ([k, v], [kv, np.ones(n, dtype=bool)])


def _multiset(datas, valids):
    k, v = datas
    kval, _ = valids
    return sorted((int(k[i]) if kval[i] else SENT, float(v[i]))
                  for i in range(len(k)))


def test_device_exchange_routes_like_host_hash(device_mesh):
    cols, valids = _kv_columns()
    xla_stats.reset()
    parts = DeviceExchange(device_mesh).exchange(cols, valids, [0], 3)
    host_pids = H.spark_partition_ids(
        [(cols[0], valids[0])], ["int64"], 3, xp=np)
    assert len(parts) == 3
    for r in range(3):
        sel = host_pids == r
        want = _multiset([c[sel] for c in cols], [v[sel] for v in valids])
        assert _multiset(*parts[r]) == want
    ss = xla_stats.shuffle_stats()
    assert ss["shuffle_device_exchanges"] == 1
    assert ss["shuffle_device_rows"] == len(cols[0])
    assert ss["shuffle_device_bytes"] > 0
    assert ss["shuffle_device_collectives"] >= 2


def test_device_exchange_skew_climbs_bucket_ladder(device_mesh):
    """Pathological skew: every row hashes to ONE destination, so the
    per-destination buckets sized for uniform traffic overflow and the
    runner must climb the capacity ladder (the last rung — the full
    per-device row count — can always hold the rows)."""
    n = 4096
    cols = [np.full(n, 7, dtype=np.int64),
            np.arange(n, dtype=np.float64)]
    valids = [np.ones(n, dtype=bool), np.ones(n, dtype=bool)]
    config.conf.set(config.MESH_EXCHANGE_SKEW.key, 1.0)
    try:
        xla_stats.reset()
        parts = DeviceExchange(device_mesh).exchange(cols, valids, [0], 3)
    finally:
        config.conf.unset(config.MESH_EXCHANGE_SKEW.key)
    target = int(H.spark_partition_ids(
        [(cols[0][:1], None)], ["int64"], 3, xp=np)[0])
    sizes = [len(parts[r][0][0]) for r in range(3)]
    assert sizes[target] == n and sum(sizes) == n
    assert _multiset(*parts[target]) == _multiset(cols, valids)
    assert xla_stats.shuffle_stats()["shuffle_device_exchanges"] == 1


def test_device_exchange_empty_and_degenerate(device_mesh):
    ex = DeviceExchange(device_mesh)
    parts = ex.exchange([np.zeros(0, np.int64)], [np.zeros(0, bool)],
                        [0], 4)
    assert len(parts) == 4
    assert all(len(d[0]) == 0 for d, _ in parts)
    with pytest.raises(DeviceExchangeError):
        ex.exchange([], [], [0], 2)


# -- planner eligibility ----------------------------------------------------

_HASH_PART = {"kind": "hash",
              "exprs": [{"kind": "column", "index": 0}],
              "num_partitions": 3}
_KV_SCHEMA = {"fields": [
    {"name": "k", "type": {"id": "int64"}, "nullable": True},
    {"name": "v", "type": {"id": "float64"}, "nullable": True}]}


def _with_shuffle_device(mode):
    config.conf.set(config.SHUFFLE_DEVICE.key, mode)


def test_planner_marks_eligible_hash_exchange():
    _with_shuffle_device("on")
    try:
        spec = exchange_device_spec(_HASH_PART, _KV_SCHEMA)
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
    assert spec == {"key_indices": [0], "num_partitions": 3}


def test_planner_declines_ineligible_exchanges():
    _with_shuffle_device("on")
    try:
        # variable-width columns still need the host row format
        utf8 = {"fields": [
            {"name": "s", "type": {"id": "utf8"}, "nullable": True}]}
        assert exchange_device_spec(_HASH_PART, utf8) is None
        # non-column key exprs: pid not computable on device
        part = dict(_HASH_PART,
                    exprs=[{"kind": "add",
                            "left": {"kind": "column", "index": 0},
                            "right": {"kind": "literal", "value": 1}}])
        assert exchange_device_spec(part, _KV_SCHEMA) is None
        # round-robin/single exchanges keep the host path
        assert exchange_device_spec(
            {"kind": "single", "num_partitions": 1}, _KV_SCHEMA) is None
        assert exchange_device_spec(None, _KV_SCHEMA) is None
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)


def test_planner_respects_mode_gates():
    _with_shuffle_device("off")
    try:
        assert exchange_device_spec(_HASH_PART, _KV_SCHEMA) is None
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
    # default 'auto': declines while compute is host-resident (the CPU
    # test platform), so existing staged runs keep the file shuffle
    from blaze_tpu.bridge.placement import host_resident
    if host_resident():
        assert exchange_device_spec(_HASH_PART, _KV_SCHEMA) is None


# -- staged end-to-end ------------------------------------------------------

def _two_stage_plan(tmp_path, n=6000, n_reduce=3):
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 200, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"in-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}


def _sorted_df(tbl):
    return tbl.to_pandas().sort_values("k").reset_index(drop=True)


def test_staged_device_shuffle_bit_identical_to_file(tmp_path, device_mesh,
                                                     staged_device):
    plan = _two_stage_plan(tmp_path)
    config.conf.set(config.SHUFFLE_DEVICE.key, "off")
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-file")).run_collect(plan))
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")

    xla_stats.reset()
    sched = DagScheduler(work_dir=str(tmp_path / "dag-dev"))
    got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)
    assert any(st.device_spec for st in sched.stages)
    ss = xla_stats.shuffle_stats()
    assert ss["shuffle_device_exchanges"] >= 1
    assert ss["shuffle_device_rows"] > 0
    assert ss["shuffle_device_fallbacks"] == 0
    assert ss["shuffle_host_bytes"] == 0


def test_staged_auto_keeps_file_shuffle_on_host(tmp_path):
    """`auto` must not engage the device lane while compute is
    host-resident — the whole point of the placement gate."""
    from blaze_tpu.bridge.placement import host_resident
    if not host_resident():
        pytest.skip("device-resident platform: auto legitimately engages")
    plan = _two_stage_plan(tmp_path, n=2000)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        xla_stats.reset()
        sched = DagScheduler(work_dir=str(tmp_path / "dag"))
        sched.run_collect(plan)
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)
    assert all(st.device_spec is None for st in sched.stages)
    assert xla_stats.shuffle_stats()["shuffle_device_exchanges"] == 0


def test_explain_analyze_reports_shuffle_footer(tmp_path, device_mesh,
                                                staged_device):
    from blaze_tpu.plan.explain import QueryProfile
    xla_stats.reset()
    before = xla_stats.snapshot()
    plan = _two_stage_plan(tmp_path)
    sched = DagScheduler(work_dir=str(tmp_path / "dag"))
    sched.run_collect(plan)
    profile = QueryProfile(
        query_id="q-shuffle", wall_ns=1, tree=sched.collect_metrics(),
        partitions=3, exec_mode="staged", xla=xla_stats.delta(before),
        kernels={}, placement="device", output_rows=0)
    text = profile.render_text()
    assert "shuffle: device=" in text
    assert "exchanges" in text
    assert "fallbacks=0" in text
