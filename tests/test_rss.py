"""Celeborn-shaped RSS backend (shuffle/rss.py): push/commit handshake
through the real rss_shuffle_writer plan hook, attempt dedup and
failure injection (ref thirdparty/auron-celeborn-0.5, shuffle/rss.rs)."""

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.bridge.resource import put_resource, remove_resource
from blaze_tpu.bridge.runtime import NativeExecutionRuntime
from blaze_tpu.memory import MemManager
from blaze_tpu.plan.proto_serde import task_definition_to_bytes
from blaze_tpu.shuffle.rss import RssPushClient


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(1 << 30)


@pytest.fixture(params=["dir", "socket"])
def make_client(request, tmp_path):
    """RSS client factory parametrized over both backends: the
    directory backend direct, and the same first-wins arbitration
    behind the socket service (shuffle data outliving its producing
    replica).  The socket client's `root` points into the server's
    storage, so the white-box filesystem assertions below hold for
    both."""
    servers, clients = [], []

    def factory(tag, num_maps, num_reduces, use_hardlinks=True):
        if request.param == "dir":
            return RssPushClient(str(tmp_path), tag, num_maps=num_maps,
                                 num_reduces=num_reduces,
                                 use_hardlinks=use_hardlinks)
        from blaze_tpu.shuffle.rss import (RssSocketClient,
                                           RssSocketServer)
        srv = RssSocketServer(str(tmp_path)).start()
        servers.append(srv)
        c = RssSocketClient(srv.url, tag, num_maps=num_maps,
                            num_reduces=num_reduces,
                            use_hardlinks=use_hardlinks)
        clients.append(c)
        return c

    yield factory
    for c in clients:
        c.close()
    for srv in servers:
        srv.stop()


def _map_td(t, tmp_path, map_id, n_maps, n_reduces, rid):
    import os

    import pyarrow.parquet as pq
    schema_d = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    per = -(-t.num_rows // n_maps)
    path = os.path.join(str(tmp_path), f"in-{rid}-{map_id}.parquet")
    if not os.path.exists(path):
        pq.write_table(t.slice(map_id * per, per), path)
    groups = [[] for _ in range(n_maps)]
    groups[map_id] = [path]
    plan = {"kind": "rss_shuffle_writer",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduces},
            "rss_resource_id": rid,
            "input": {"kind": "parquet_scan", "schema": schema_d,
                      "file_groups": groups}}
    return {"stage_id": 7, "partition_id": map_id,
            "num_partitions": n_maps, "plan": plan}


def _run_map(t, tmp_path, client, map_id, n_maps, n_reduces, attempt=0,
             die_after_push=False):
    """One map task through the wire; returns the writer (committed
    unless told to die before the handshake)."""
    writer = client.partition_writer(map_id, attempt)
    rid = f"rss-test-{client.shuffle_id}-m{map_id}"
    put_resource(rid, writer)
    try:
        td = task_definition_to_bytes(
            _map_td(t, tmp_path, map_id, n_maps, n_reduces, rid))
        rt = NativeExecutionRuntime(td).start()
        try:
            for _ in rt.batches():
                pass
        finally:
            rt.finalize()
        if not die_after_push:
            writer.commit()
    finally:
        remove_resource(rid)
    return writer


def _reduce_all(t, client, n_reduces):
    """Read every partition back through ipc_reader; returns the table."""
    schema_d = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    rid = f"rss-read-{client.shuffle_id}"
    put_resource(rid, lambda p: client.reader_blocks(p, timeout_s=5.0))
    out = []
    try:
        for r in range(n_reduces):
            td = task_definition_to_bytes(
                {"stage_id": 8, "partition_id": r,
                 "num_partitions": n_reduces,
                 "plan": {"kind": "ipc_reader", "resource_id": rid,
                          "schema": schema_d,
                          "num_partitions": n_reduces}})
            rt = NativeExecutionRuntime(td).start()
            try:
                out.extend(b for b in rt.batches() if b.num_rows)
            finally:
                rt.finalize()
    finally:
        remove_resource(rid)
    if not out:
        return pa.table({"k": pa.array([], pa.int64()),
                         "v": pa.array([], pa.float64())})
    return pa.Table.from_batches(out)


def _table(n=5000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({"k": pa.array(rng.integers(0, 500, n)),
                     "v": pa.array(np.round(rng.random(n) * 10, 3))})


def test_push_commit_read_roundtrip(tmp_path, make_client):
    t = _table()
    client = make_client("s1", num_maps=3, num_reduces=4)
    for m in range(3):
        _run_map(t, tmp_path, client, m, 3, 4)
    got = _reduce_all(t, client, 4)
    assert got.num_rows == t.num_rows
    assert abs(pa.compute.sum(got["v"]).as_py()
               - pa.compute.sum(t["v"]).as_py()) < 1e-9
    # hash partitioning really spread the rows
    assert all(len(client.reader_blocks(p, 1.0)) > 0 for p in range(4))


def test_failed_attempt_is_ignored(tmp_path, make_client):
    """Failure injection: attempt 0 of map 1 pushes frames but dies
    before MapperEnd; the retry (attempt 1) commits.  Readers must see
    exactly one attempt's data — no loss, no duplication."""
    t = _table()
    client = make_client("s2", num_maps=2, num_reduces=3)
    _run_map(t, tmp_path, client, 0, 2, 3)
    _run_map(t, tmp_path, client, 1, 2, 3, attempt=0, die_after_push=True)  # dies
    _run_map(t, tmp_path, client, 1, 2, 3, attempt=1)                       # retry
    got = _reduce_all(t, client, 3)
    assert got.num_rows == t.num_rows
    assert abs(pa.compute.sum(got["v"]).as_py()
               - pa.compute.sum(t["v"]).as_py()) < 1e-9


def test_idempotent_repush(tmp_path, make_client):
    """A task retried WITH THE SAME attempt id (speculative duplicate)
    re-pushes identical frames; rename-idempotence collapses them."""
    t = _table(n=2000)
    client = make_client("s3", num_maps=1, num_reduces=2)
    _run_map(t, tmp_path, client, 0, 1, 2, attempt=0, die_after_push=True)
    _run_map(t, tmp_path, client, 0, 1, 2, attempt=0)  # same attempt, full rerun
    got = _reduce_all(t, client, 2)
    assert got.num_rows == t.num_rows


def test_missing_map_times_out(tmp_path, make_client):
    t = _table(n=100)
    client = make_client("s4", num_maps=2, num_reduces=1)
    _run_map(t, tmp_path, client, 0, 2, 1)
    with pytest.raises(TimeoutError, match="never committed"):
        client.wait_for_maps(timeout_s=0.3)


def test_lost_push_detected(tmp_path, make_client):
    """A committed manifest whose frames vanished (worker data loss)
    must fail loudly, not return partial data."""
    import glob, os
    t = _table(n=3000)
    client = make_client("s5", num_maps=1, num_reduces=2)
    _run_map(t, tmp_path, client, 0, 1, 2)
    victims = glob.glob(os.path.join(client.root, "part-0", "*.push"))
    assert victims
    os.unlink(victims[0])
    with pytest.raises(IOError, match="lost pushes"):
        client.reader_blocks(0, timeout_s=1.0)


def test_crashed_run_leftover_frames_tolerated(tmp_path, make_client):
    """A crashed run of the SAME attempt left higher-seq frames the
    committed retry never re-pushed; those are garbage, not lost pushes
    — the committed prefix must read cleanly."""
    client = make_client("s6", num_maps=1, num_reduces=1)
    # crashed run pushed 3 frames, no commit
    for seq in range(3):
        client._push(0, 0, 0, seq, b"frame%d" % seq)
    # retry (same attempt) re-pushes only 2 frames and commits 2
    client._commit(0, 0, {0: 2})
    blocks = client.reader_blocks(0, timeout_s=1.0)
    assert blocks == [b"frame0", b"frame1"]


def _race_two_attempts(make_client, tag, use_hardlinks):
    """Two DISTINCT attempts of map 0 push different payloads and both
    reach the commit point (the forced loser-commit-race shape).  The
    first committer must win, the second must be rejected, and readers
    must see exactly the winner's frames."""
    client = make_client(tag, num_maps=1, num_reduces=1,
                         use_hardlinks=use_hardlinks)
    client._push(0, 0, 0, 0, b"attempt0-frame")
    client._push(0, 1, 0, 0, b"attempt1-frame")
    assert client._commit(0, 0, {0: 1}) is True
    assert client._commit(0, 1, {0: 1}) is False   # late attempt rejected
    assert client._committed_attempt(0) == 0
    # idempotent re-commit of the WINNER stays accepted (lost result
    # frame -> task-level retry of the same attempt)
    assert client._commit(0, 0, {0: 1}) is True
    blocks = client.reader_blocks(0, timeout_s=1.0)
    assert blocks == [b"attempt0-frame"]  # loser frames ignored


def test_distinct_attempt_first_wins_hardlink(make_client):
    _race_two_attempts(make_client, "race-hl", use_hardlinks=True)


def test_distinct_attempt_first_wins_no_hardlink(tmp_path, make_client):
    """The FUSE/object-store fallback must arbitrate via the O_EXCL
    claim file, not last-wins os.replace."""
    _race_two_attempts(make_client, "race-claim", use_hardlinks=False)
    # the claim file names the winner
    import os
    claim = os.path.join(str(tmp_path), "rss-race-claim",
                         "commit-m0.owner")
    with open(claim) as f:
        assert f.read().strip() == "0"


def test_file_tier_distinct_attempt_first_wins(tmp_path):
    """File-tier arbitration: each attempt writes a private
    `<base>.a<N>.data/.index` pair; the first promote wins via the
    O_EXCL claim + single os.replace of the index, the loser's files
    are deleted, and resolve_attempt_data maps the canonical path to
    the winner's data file."""
    import os

    from blaze_tpu.shuffle.writer import (promote_attempt_output,
                                          resolve_attempt_data)
    base = os.path.join(str(tmp_path), "s0-7-0")
    paths = {}
    for a in (0, 1):
        paths[a] = (f"{base}.a{a}.data", f"{base}.a{a}.index")
        with open(paths[a][0], "wb") as f:
            f.write(b"data-a%d" % a)
        with open(paths[a][1], "wb") as f:
            f.write(b"index-a%d" % a)
    assert promote_attempt_output(*paths[1]) is True    # attempt 1 wins
    assert promote_attempt_output(*paths[0]) is False   # loser rejected
    data, attempt = resolve_attempt_data(base + ".data")
    assert attempt == 1 and data.endswith(".a1.data")
    with open(base + ".index", "rb") as f:
        assert f.read() == b"index-a1"   # canonical index = winner's
    with open(data, "rb") as f:
        assert f.read() == b"data-a1"
    # the loser's private files are gone — unreadable by construction
    assert not os.path.exists(paths[0][0])
    assert not os.path.exists(paths[0][1])
    # idempotent re-promotion of the winner is still the winner
    # (nothing left to move, but the verdict must not flip)
    assert promote_attempt_output(*paths[1]) is True
    # un-suffixed paths are untouched by the arbitration
    assert promote_attempt_output(base + ".data", base + ".index") is None


def test_file_tier_concurrent_promotion_single_winner(tmp_path):
    """N threads race promote_attempt_output for distinct attempts;
    exactly one may win and every loser's files must be gone."""
    import os
    import threading

    from blaze_tpu.shuffle.writer import (promote_attempt_output,
                                          resolve_attempt_data)
    base = os.path.join(str(tmp_path), "s0-9-3")
    n = 8
    for a in range(n):
        with open(f"{base}.a{a}.data", "wb") as f:
            f.write(b"d%d" % a)
        with open(f"{base}.a{a}.index", "wb") as f:
            f.write(b"i%d" % a)
    verdicts = [None] * n
    barrier = threading.Barrier(n)

    def go(a):
        barrier.wait()
        verdicts[a] = promote_attempt_output(f"{base}.a{a}.data",
                                             f"{base}.a{a}.index")
    threads = [threading.Thread(target=go, args=(a,)) for a in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert verdicts.count(True) == 1
    assert verdicts.count(False) == n - 1
    winner = verdicts.index(True)
    data, attempt = resolve_attempt_data(base + ".data")
    assert attempt == winner
    with open(data, "rb") as f:
        assert f.read() == b"d%d" % winner
    leftovers = [a for a in range(n) if a != winner
                 and (os.path.exists(f"{base}.a{a}.data")
                      or os.path.exists(f"{base}.a{a}.index"))]
    assert leftovers == []
