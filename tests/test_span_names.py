"""Span-name conformance: the tracing registry (tracing.SPAN_NAMES) is
the contract for the whole observability surface.  Every registered
name must be exercised by a test (or the bench obs leg), documented in
docs/observability.md, and actually emitted somewhere in the engine —
so a new span cannot land without coverage or docs, and a renamed or
removed emitter cannot silently orphan its registry entry.  Mirrors
tests/test_fault_sites.py for chaos sites."""

import os
import re

from blaze_tpu.bridge import tracing

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_PKG = os.path.join(_REPO, "blaze_tpu")

# tracing.span / instant / emit_span call with a literal (or f-string)
# name as the first argument, possibly wrapped to the next line
_EMIT_RE = re.compile(
    r"(?:span|instant|emit_span)\(\s*f?\"([^\"\n]+)\"")


def _corpus() -> str:
    chunks = []
    for name in sorted(os.listdir(_HERE)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        if name == os.path.basename(__file__):
            continue  # self-references must not count as coverage
        with open(os.path.join(_HERE, name)) as f:
            chunks.append(f.read())
    with open(os.path.join(_REPO, "bench.py")) as f:
        chunks.append(f.read())
    return "\n".join(chunks)


def _emitted_names() -> set:
    """Every span name the engine can emit, harvested from source.
    f-string names collapse to their literal prefix + '*' so dynamic
    families (operator:<name>) map onto their wildcard registration."""
    names = set()
    for root, _dirs, files in os.walk(_PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn)) as f:
                src = f.read()
            for m in _EMIT_RE.finditer(src):
                name = m.group(1)
                if "{" in name:
                    name = name.split("{", 1)[0] + "*"
                names.add(name)
    return names


def test_every_span_name_is_exercised():
    corpus = _corpus()
    missing = []
    for name in tracing.SPAN_NAMES:
        if name.endswith("*"):
            # dynamic family: any member with the literal prefix counts
            ok = name[:-1] in corpus
        else:
            # word-boundary safe for snake_case names: "task" must not
            # match inside "task_attempt" or "worker_task"
            ok = re.search(rf"(?<![-\w]){re.escape(name)}(?![-\w])",
                           corpus)
        if not ok:
            missing.append(name)
    assert not missing, (
        f"span names with no test or bench coverage: {missing} — add a "
        f"test that emits or asserts on the span (see tests/"
        f"test_tracing.py)")


def test_every_span_name_is_documented():
    with open(os.path.join(_REPO, "docs", "observability.md")) as f:
        doc = f.read()
    undocumented = [n for n in tracing.SPAN_NAMES if n not in doc]
    assert not undocumented, (
        f"span names missing from docs/observability.md: {undocumented}")
    assert all(d.strip() for d in tracing.SPAN_NAMES.values()), \
        "every registry entry needs a one-line doc naming its emitter"


def test_no_dead_or_unregistered_span_names():
    emitted = _emitted_names()
    unregistered = sorted(n for n in emitted if n not in tracing.SPAN_NAMES)
    assert not unregistered, (
        f"emitted but not registered (tracing raises at runtime when "
        f"enabled): {unregistered}")
    dead = sorted(n for n in tracing.SPAN_NAMES if n not in emitted)
    assert not dead, (
        f"registered but never emitted anywhere in blaze_tpu/: {dead} — "
        f"remove the registry entry or wire up the emitter")
