"""Streaming sort-merge join tests: join-type matrix vs the hash-join
result (and pandas), sorted-children passthrough, SHJ->SMJ fallback
(ref joins/test.rs matrix, sort_merge_join_exec.rs:397)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.ops import MemoryScanExec, SortExec
from blaze_tpu.ops.joins import JoinType
from blaze_tpu.ops.joins.exec import (ShuffledHashJoinExec,
                                      SortMergeJoinExec)


def _tables(seed=0, n_left=4000, n_right=3000, nulls=True):
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, 500, n_left).astype(float)
    rk = rng.integers(0, 500, n_right).astype(float)
    if nulls:
        lk[rng.random(n_left) < 0.03] = np.nan
        rk[rng.random(n_right) < 0.03] = np.nan
    left = pa.table({
        "lk": pa.array([None if np.isnan(x) else int(x) for x in lk],
                       type=pa.int64()),
        "lv": pa.array(np.round(rng.random(n_left) * 10, 3))})
    right = pa.table({
        "rk": pa.array([None if np.isnan(x) else int(x) for x in rk],
                       type=pa.int64()),
        "rv": pa.array(np.round(rng.random(n_right) * 10, 3))})
    return left, right


def _run(plan):
    out = [b.compact().to_arrow() for b in plan.execute(0)]
    out = [b for b in out if b.num_rows]
    if not out:
        return pd.DataFrame()
    return pa.Table.from_batches(out).to_pandas()


def _sorted_frames(df):
    if df.empty:
        return df
    return df.sort_values(list(df.columns)).reset_index(drop=True)


@pytest.fixture(params=["acero", "streaming"])
def smj_path(request):
    """Both SMJ host paths stay covered: the Acero materialized join
    and the streaming run-cursor merge it falls back to."""
    key = config.SMJ_ACERO_ENABLE.key
    old = config.SMJ_ACERO_ENABLE.get()
    config.conf.set(key, request.param == "acero")
    yield request.param
    config.conf.set(key, old)


@pytest.mark.parametrize("jt", list(JoinType))
def test_smj_matches_hash_join(jt, smj_path):
    left, right = _tables()
    smj = SortMergeJoinExec(
        MemoryScanExec.from_arrow(left, batch_rows=512),
        MemoryScanExec.from_arrow(right, batch_rows=512),
        [col(0)], [col(0)], jt)
    shj = ShuffledHashJoinExec(
        MemoryScanExec.from_arrow(left, batch_rows=512),
        MemoryScanExec.from_arrow(right, batch_rows=512),
        [col(0)], [col(0)], jt)
    a = _sorted_frames(_run(smj))
    b = _sorted_frames(_run(shj))
    assert len(a) == len(b), (jt, len(a), len(b))
    if len(a):
        pd.testing.assert_frame_equal(a, b, check_dtype=False,
                                      check_exact=False, atol=1e-9)


def test_smj_with_join_filter(smj_path):
    left, right = _tables(seed=3, n_left=1000, n_right=800)
    flt = BinaryExpr(">", col(1), col(3))  # lv > rv on joined schema
    smj = SortMergeJoinExec(
        MemoryScanExec.from_arrow(left), MemoryScanExec.from_arrow(right),
        [col(0)], [col(0)], JoinType.INNER, join_filter=flt)
    shj = ShuffledHashJoinExec(
        MemoryScanExec.from_arrow(left), MemoryScanExec.from_arrow(right),
        [col(0)], [col(0)], JoinType.INNER, join_filter=flt)
    a = _sorted_frames(_run(smj))
    b = _sorted_frames(_run(shj))
    assert len(a) == len(b)
    if len(a):
        pd.testing.assert_frame_equal(a, b, check_dtype=False,
                                      check_exact=False, atol=1e-9)


def test_smj_multi_key(smj_path):
    rng = np.random.default_rng(5)
    left = pa.table({"a": pa.array(rng.integers(0, 20, 2000)),
                     "b": pa.array(rng.integers(0, 10, 2000)),
                     "v": pa.array(rng.random(2000))})
    right = pa.table({"a": pa.array(rng.integers(0, 20, 1500)),
                      "b": pa.array(rng.integers(0, 10, 1500)),
                      "w": pa.array(rng.random(1500))})
    smj = SortMergeJoinExec(
        MemoryScanExec.from_arrow(left, batch_rows=256),
        MemoryScanExec.from_arrow(right, batch_rows=256),
        [col(0), col(1)], [col(0), col(1)], JoinType.INNER)
    got = _run(smj)
    want = left.to_pandas().merge(right.to_pandas(), on=["a", "b"])
    assert len(got) == len(want)


def test_smj_exploits_presorted_children():
    """A SortExec child on the join keys must stream through unwrapped."""
    left, right = _tables(seed=7, n_left=500, n_right=400)
    ls = SortExec(MemoryScanExec.from_arrow(left), [(col(0), False, True)])
    rs = SortExec(MemoryScanExec.from_arrow(right), [(col(0), False, True)])
    smj = SortMergeJoinExec(ls, rs, [col(0)], [col(0)], JoinType.INNER)
    assert smj._sorted_child(0) is ls
    assert smj._sorted_child(1) is rs
    got = _run(smj)
    want = left.to_pandas().dropna(subset=["lk"]).merge(
        right.to_pandas().dropna(subset=["rk"]),
        left_on="lk", right_on="rk")
    assert len(got) == len(want)


def test_smj_string_keys(smj_path):
    left = pa.table({"k": pa.array(["a", "b", "b", None, "c"]),
                     "v": pa.array([1, 2, 3, 4, 5], type=pa.int64())})
    right = pa.table({"k": pa.array(["b", "c", "c", None]),
                      "w": pa.array([10, 20, 30, 40], type=pa.int64())})
    smj = SortMergeJoinExec(
        MemoryScanExec.from_arrow(left), MemoryScanExec.from_arrow(right),
        [col(0)], [col(0)], JoinType.FULL)
    got = _run(smj)
    # inner pairs: 2 left 'b' rows x 1 right 'b' + 1 left 'c' x 2 right 'c';
    # unmatched left: 'a' and the NULL key; unmatched right: the NULL key
    assert len(got) == 2 + 2 + 2 + 1
    assert got.w.isna().sum() == 2   # unmatched left rows
    assert got.v.isna().sum() == 1   # unmatched right row


def test_shj_falls_back_to_smj_on_large_build():
    left, right = _tables(seed=11, n_left=3000, n_right=2500)
    config.conf.set(config.SMJ_FALLBACK_ENABLE.key, True)
    config.conf.set(config.SMJ_FALLBACK_ROWS_THRESHOLD.key, 100)
    try:
        shj = ShuffledHashJoinExec(
            MemoryScanExec.from_arrow(left),
            MemoryScanExec.from_arrow(right),
            [col(0)], [col(0)], JoinType.INNER)
        got = _sorted_frames(_run(shj))
        assert shj.metrics.get("smj_fallback") >= 1
    finally:
        config.conf.unset(config.SMJ_FALLBACK_ENABLE.key)
        config.conf.unset(config.SMJ_FALLBACK_ROWS_THRESHOLD.key)
    want = left.to_pandas().dropna(subset=["lk"]).merge(
        right.to_pandas().dropna(subset=["rk"]),
        left_on="lk", right_on="rk")
    assert len(got) == len(want)


def test_smj_nan_float_keys_match_like_spark(smj_path):
    """Spark treats NaN as a NORMAL value in join keys (NaN semantics
    doc; NormalizeFloatingNumbers applies to join keys): NaN joins NaN.
    NULL keys still never match.  SMJ, the vectorized hash probe, and
    the Acero host path must all agree."""
    left = pa.table({"lk": pa.array([1.0, 2.0, float("nan"), None]),
                     "lv": pa.array([10, 20, 30, 40], type=pa.int64())})
    right = pa.table({"rk": pa.array([2.0, 3.0, float("nan"), None]),
                      "rv": pa.array([200, 300, 400, 500],
                                     type=pa.int64())})
    smj = SortMergeJoinExec(
        MemoryScanExec.from_arrow(left), MemoryScanExec.from_arrow(right),
        [col(0)], [col(0)], JoinType.INNER)
    shj = ShuffledHashJoinExec(
        MemoryScanExec.from_arrow(left), MemoryScanExec.from_arrow(right),
        [col(0)], [col(0)], JoinType.INNER)
    a, b = _run(smj), _run(shj)
    assert len(a) == len(b) == 2  # 2.0 match + NaN match; nulls drop
    a = a.sort_values("lv")
    assert a.iloc[0].lk == 2.0 and a.iloc[0].rv == 200
    assert a.iloc[1].rv == 400  # NaN joined NaN


def test_smj_acero_overflow_resumes_streaming():
    """Collect-budget overflow mid-Acero-collection hands the consumed
    chunks to the streaming merge (sorted children) or re-executes
    (unsorted children) — results identical either way."""
    left, right = _tables(seed=3)
    key = config.FUSED_HOST_COLLECT_ROWS.key
    old = config.FUSED_HOST_COLLECT_ROWS.get()
    try:
        for presort in (True, False):
            l_scan = MemoryScanExec.from_arrow(left, batch_rows=256)
            r_scan = MemoryScanExec.from_arrow(right, batch_rows=256)
            lk, rk = [col(0, "lk")], [col(0, "rk")]
            if presort:
                l_in = SortExec(l_scan, [(lk[0], False, True)])
                r_in = SortExec(r_scan, [(rk[0], False, True)])
            else:
                l_in, r_in = l_scan, r_scan
            config.conf.set(key, old)
            want = _run(SortMergeJoinExec(l_in, r_in, lk, rk,
                                          JoinType.INNER))
            config.conf.set(key, 500)  # forces overflow on both sides
            got = _run(SortMergeJoinExec(l_in, r_in, lk, rk,
                                         JoinType.INNER))
            assert len(got) == len(want), (presort, len(got), len(want))
            gs = got.sort_values(list(got.columns)).reset_index(drop=True)
            ws = want.sort_values(list(want.columns)).reset_index(drop=True)
            pd.testing.assert_frame_equal(gs, ws)
    finally:
        config.conf.set(key, old)
