"""Decimals on the device lanes (ISSUE 20): p<=18 decimal128 rides the
int lanes as scaled int64 (int32 for p<=9), unequal-scale comparisons
rescale through the two-limb int128 kernels, and the device exchange
carries decimals as unscaled longs — all bit-identical to the exact
host `decimal.Decimal` path, with overflow promoting to host (null per
Spark CheckOverflow), never wrapping.  Knob off = byte-identical seed
behaviour with the eviction reason accounted."""

import decimal as pydec
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.batch import ColumnBatch, DeviceColumn, decimal_from_unscaled
from blaze_tpu.bridge import xla_stats
from blaze_tpu.cache import reset_cache
from blaze_tpu.exprs.base import ColVal, col
from blaze_tpu.kernels import decimal128 as d128
from blaze_tpu.memory import MemManager
from blaze_tpu.plan.stages import DagScheduler
from blaze_tpu.schema import decimal

_U64 = (1 << 64) - 1
_M128 = 1 << 128


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    reset_cache()
    try:
        yield
    finally:
        faults.clear()
        reset_cache()


@pytest.fixture
def dec_on():
    config.conf.set(config.ENCODING_DECIMAL_ENABLE.key, True)
    try:
        yield
    finally:
        config.conf.unset(config.ENCODING_DECIMAL_ENABLE.key)


@pytest.fixture
def staged_path():
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


# -- int128 reference helpers ------------------------------------------------

def _signed128(h, l):
    """(hi int64, lo int64) limb pair -> python int."""
    v = ((int(h) << 64) + (int(l) & _U64)) & (_M128 - 1)
    return v - _M128 if v >= (1 << 127) else v


def _pair(vals):
    """python ints -> (hi, lo) int64 numpy limb arrays."""
    hs, ls = [], []
    for v in vals:
        u = int(v) & (_M128 - 1)
        lo, hi = u & _U64, (u >> 64) & _U64
        ls.append(lo - (1 << 64) if lo >= (1 << 63) else lo)
        hs.append(hi - (1 << 64) if hi >= (1 << 63) else hi)
    return (np.array(hs, dtype=np.int64), np.array(ls, dtype=np.int64))


def _rand128(rng, n):
    """Mixed-magnitude int128 sample: full-range, int64-range, tiny,
    and the limb-boundary seams (+-2^63, +-2^64, 0, -1)."""
    out = [0, -1, 1, (1 << 63) - 1, -(1 << 63), 1 << 63, 1 << 64,
           -(1 << 64), (1 << 126), -(1 << 126)]
    for _ in range(n - len(out)):
        bits = int(rng.integers(1, 127))
        v = int(rng.integers(0, 1 << min(bits, 62))) << max(0, bits - 62)
        out.append(-v if rng.random() < 0.5 else v)
    return out


# -- kernel properties vs python-int reference -------------------------------

def test_add_sub_128_matches_python_ints():
    rng = np.random.default_rng(3)
    a = _rand128(rng, 64)
    b = _rand128(rng, 64)
    rng.shuffle(b)
    ah, al = _pair(a)
    bh, bl = _pair(b)
    rh, rl = d128.add128(np, ah, al, bh, bl)
    sh, sl = d128.sub128(np, ah, al, bh, bl)
    for i, (x, y) in enumerate(zip(a, b)):
        want_add = ((x + y) + (1 << 127)) % _M128 - (1 << 127)
        want_sub = ((x - y) + (1 << 127)) % _M128 - (1 << 127)
        assert _signed128(rh[i], rl[i]) == want_add, (x, y)
        assert _signed128(sh[i], sl[i]) == want_sub, (x, y)


def test_neg_fits_and_overflow_flags():
    vals = [0, 1, -1, 1 << 63, -(1 << 63), (1 << 63) - 1, 1 << 100]
    h, l = _pair(vals)
    nh, nl = d128.neg128(np, h, l)
    for i, v in enumerate(vals):
        assert _signed128(nh[i], nl[i]) == -v
    fits = d128.fits_int64(np, h, l)
    assert fits.tolist() == [True, True, True, False, True, True, False]
    # same-sign add whose result flips sign = overflow; mixed signs never
    ah, al = _pair([1 << 126, 1 << 126, -(1 << 126) - 5, 5])
    bh, bl = _pair([1 << 126, -(1 << 126), -(1 << 126) - 5, -7])
    rh, _ = d128.add128(np, ah, al, bh, bl)
    ovf = d128.add_overflows(np, ah, bh, rh)
    assert ovf.tolist() == [True, False, True, False]


def test_mul_pow10_matches_python_ints():
    rng = np.random.default_rng(11)
    vals = [0, 1, -1, 10 ** 18 - 1, -(10 ** 18) + 1] + \
        [int(rng.integers(-10 ** 18, 10 ** 18)) for _ in range(40)]
    for k in (0, 1, 9, 10, 18, 20):
        h, l = d128.from_int64(np, np.array(vals, dtype=np.int64))
        rh, rl = d128.mul_pow10(np, h, l, k)
        for i, v in enumerate(vals):
            # contract: |v| < 10^18, k <= 20 -> exact inside int128
            assert _signed128(rh[i], rl[i]) == v * 10 ** k, (v, k)


def test_compare128_matches_python_ints():
    rng = np.random.default_rng(29)
    a = _rand128(rng, 80)
    b = list(a[:20]) + _rand128(rng, 60)  # force some equal pairs
    rng.shuffle(a)
    ah, al = _pair(a)
    bh, bl = _pair(b)
    lt = d128.lt128(np, ah, al, bh, bl)
    eq = d128.eq128(np, ah, al, bh, bl)
    for i, (x, y) in enumerate(zip(a, b)):
        assert bool(lt[i]) == (x < y), (x, y)
        assert bool(eq[i]) == (x == y), (x, y)


def test_u_lt_unsigned_semantics():
    a = np.array([0, -1, 1, -(1 << 63)], dtype=np.int64)
    b = np.array([-1, 0, 2, 0], dtype=np.int64)
    # as unsigned: 0 < 2^64-1;  2^64-1 > 0;  1 < 2;  2^63 > 0
    assert d128.u_lt(np, a, b).tolist() == [True, False, True, False]


# -- BigInteger minimal bytes + wide-decimal hash ----------------------------

def _ref_biginteger_bytes(v: int) -> bytes:
    """java.math.BigInteger.toByteArray (two's complement, minimal)."""
    n = (v.bit_length() // 8 + 1) if v >= 0 \
        else ((v + 1).bit_length() // 8 + 1)
    return v.to_bytes(n, "big", signed=True)


_BYTE_EDGE_VALS = [0, 1, -1, 127, 128, -128, -129, 255, 256, -256,
                   (1 << 63) - 1, -(1 << 63), 1 << 63, 1 << 64,
                   -(1 << 64), 10 ** 18, -(10 ** 18),
                   (10 ** 18) * (10 ** 20), -((10 ** 18) * (10 ** 20))]


def test_minimal_be_bytes_matches_biginteger():
    h, l = _pair(_BYTE_EDGE_VALS)
    mat, lengths = d128.minimal_be_bytes(h, l)
    for i, v in enumerate(_BYTE_EDGE_VALS):
        ref = _ref_biginteger_bytes(v)
        assert int(lengths[i]) == len(ref), v
        assert bytes(mat[i, :len(ref)]) == ref, v
        assert not mat[i, len(ref):].any()  # left-aligned, zero padding


def test_spark_decimal128_hash_matches_reference():
    from blaze_tpu.kernels.hashing import murmur3_hash_bytes
    rng = np.random.default_rng(17)
    vals = _BYTE_EDGE_VALS + _rand128(rng, 40)
    n = len(vals)
    ref_mat = np.zeros((n, 16), dtype=np.uint8)
    ref_len = np.zeros(n, dtype=np.int32)
    for i, v in enumerate(vals):
        b = _ref_biginteger_bytes(v)
        ref_len[i] = len(b)
        ref_mat[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
    want = murmur3_hash_bytes(ref_mat, ref_len,
                              np.full(n, 42, dtype=np.uint32), np)
    h, l = _pair(vals)
    got = d128.spark_decimal128_hash(h, l)
    assert np.array_equal(np.asarray(got), np.asarray(want))


# -- unequal-scale comparisons: limb lane vs decimal.Decimal -----------------

_OPS = ("==", "!=", "<", "<=", ">", ">=", "<=>")


@pytest.mark.parametrize("lp,ls,rp,rs", [
    (18, 2, 18, 6),    # moderate scale delta with crafted equal pairs
    (18, 0, 18, 18),   # the extreme: delta 18 at full p=18 magnitudes
])
def test_compare_colvals_all_ops_vs_decimal(dec_on, lp, ls, rp, rs):
    rng = np.random.default_rng(41)
    n = 96
    lmax = 10 ** lp - 1
    rmax = 10 ** rp - 1
    a = rng.integers(-lmax, lmax, n).astype(np.int64)
    b = rng.integers(-rmax, rmax, n).astype(np.int64)
    # limb-boundary extremes and equal-value pairs across scales
    a[:6] = [lmax, -lmax, 0, 1, -1, 150 if ls == 2 else lmax]
    b[:6] = [rmax, -rmax, 0, 1, -1,
             1500000 if rs == 6 else rmax]  # 1.50 == 1.500000
    av = rng.random(n) > 0.12
    bv = rng.random(n) > 0.12
    ldt, rdt = decimal(lp, ls), decimal(rp, rs)
    a_cv = ColVal(ldt, data=a, validity=av)
    b_cv = ColVal(rdt, data=b, validity=bv)
    ref_a = [Decimal(int(x)).scaleb(-ls) for x in a]
    ref_b = [Decimal(int(y)).scaleb(-rs) for y in b]
    before = xla_stats.encoding_stats()["decimal_limb_dispatches"]
    for op in _OPS:
        out = d128.compare_colvals(op, a_cv, b_cv, ldt, rdt)
        for i in range(n):
            x, y = ref_a[i], ref_b[i]
            if op == "<=>":
                want = (x == y and av[i] and bv[i]) or \
                    (not av[i] and not bv[i])
                assert bool(out.validity[i])
                assert bool(out.data[i]) == want, (op, i, x, y)
                continue
            if not (av[i] and bv[i]):
                assert not bool(out.validity[i])
                assert not bool(out.data[i])  # null rows read False
                continue
            want = {"==": x == y, "!=": x != y, "<": x < y,
                    "<=": x <= y, ">": x > y, ">=": x >= y}[op]
            assert bool(out.data[i]) == want, (op, i, x, y)
    assert xla_stats.encoding_stats()["decimal_limb_dispatches"] > before


def test_binary_expr_routes_unequal_scale_compare_to_limbs(dec_on):
    """Through the real expression layer: a device-form unequal-scale
    decimal predicate stays vectorized (limb counter fires) and agrees
    with the exact host Decimal answer."""
    from blaze_tpu.exprs.binary import BinaryExpr
    vals_a = [Decimal("1.50"), Decimal("-7.25"), None, Decimal("0.01")]
    vals_b = [Decimal("1.500000"), Decimal("-7.250001"), Decimal("2.0"),
              None]
    t = pa.table({"a": pa.array(vals_a, type=pa.decimal128(12, 2)),
                  "b": pa.array(vals_b, type=pa.decimal128(12, 6))})
    batch = ColumnBatch.from_arrow(t)
    before = xla_stats.encoding_stats()["decimal_limb_dispatches"]
    got = BinaryExpr("<=", col(0), col(1)).evaluate(batch) \
        .to_host(batch.num_rows)
    assert xla_stats.encoding_stats()["decimal_limb_dispatches"] > before
    assert got.to_pylist() == [True, False, None, None]


def test_equal_scale_device_add_matches_exact_host():
    """p<=18 equal-scale '+' takes the vectorized unscaled-int64 path;
    it must agree digit-for-digit with the exact host path."""
    from blaze_tpu.exprs.binary import BinaryExpr
    rng = np.random.default_rng(53)
    n = 200
    ua = rng.integers(-10 ** 9, 10 ** 9, n)
    ub = rng.integers(-10 ** 9, 10 ** 9, n)
    da = [Decimal(int(v)).scaleb(-2) if rng.random() > 0.1 else None
          for v in ua]
    db = [Decimal(int(v)).scaleb(-2) if rng.random() > 0.1 else None
          for v in ub]
    t = pa.table({"a": pa.array(da, type=pa.decimal128(10, 2)),
                  "b": pa.array(db, type=pa.decimal128(10, 2))})
    batch = ColumnBatch.from_arrow(t)
    out = BinaryExpr("+", col(0), col(1)).evaluate(batch)
    assert out.dtype.precision == 11 and out.dtype.scale == 2
    want = [None if (x is None or y is None) else x + y
            for x, y in zip(da, db)]
    assert out.to_host(batch.num_rows).to_pylist() == want


def test_decimal_overflow_promotes_to_host_null_never_wraps():
    """'/' widens past the device contract -> exact host path; rows
    whose result exceeds the capped precision go NULL (Spark
    CheckOverflow), they never wrap; /0 is NULL non-ANSI."""
    from blaze_tpu.exprs.binary import BinaryExpr
    a_vals = [Decimal(10 ** 17), Decimal(4), Decimal(10)]
    b_vals = [Decimal(1).scaleb(-18), Decimal(0), Decimal("0.5")]
    t = pa.table({"a": pa.array(a_vals, type=pa.decimal128(18, 0)),
                  "b": pa.array(b_vals, type=pa.decimal128(18, 18))})
    batch = ColumnBatch.from_arrow(t)
    out = BinaryExpr("/", col(0), col(1)).evaluate(batch)
    assert not out.is_device  # promoted to the exact host form
    got = out.to_host(batch.num_rows).to_pylist()
    assert got[0] is None          # 10^35 overflows decimal(38,6)
    assert got[1] is None           # divide by zero -> null (non-ANSI)
    assert got[2] == Decimal("20")  # in-range rows stay exact


# -- arrow boundary: unscaled rebuild + tier counters ------------------------

def test_decimal_from_unscaled_round_trip():
    rng = np.random.default_rng(61)
    unscaled = rng.integers(-10 ** 14, 10 ** 14, 64)
    unscaled[:4] = [10 ** 18 - 1, -(10 ** 18) + 1, 0, -1]
    valid = rng.random(64) > 0.2
    t = pa.decimal128(18, 4)
    got = decimal_from_unscaled(unscaled.astype(np.int64), valid, t)
    want = pa.array([Decimal(int(v)).scaleb(-4) if ok else None
                     for v, ok in zip(unscaled, valid)], type=t)
    assert got.equals(want)
    # all-valid fast path drops the validity buffer entirely
    got2 = decimal_from_unscaled(unscaled.astype(np.int64), None, t)
    assert got2.null_count == 0
    assert got2.to_pylist() == [Decimal(int(v)).scaleb(-4)
                                for v in unscaled]


def test_scaled_int_tier_counters_and_round_trip(dec_on):
    rng = np.random.default_rng(71)
    narrow = pa.array([Decimal(int(v)).scaleb(-2)
                       for v in rng.integers(-10 ** 4, 10 ** 4, 50)],
                      type=pa.decimal128(7, 2))
    wide = pa.array([Decimal(int(v)).scaleb(-2)
                     for v in rng.integers(-10 ** 9, 10 ** 9, 50)],
                    type=pa.decimal128(12, 2))
    before = xla_stats.encoding_stats()
    c7 = DeviceColumn.from_arrow(narrow, decimal(7, 2), 64)
    c12 = DeviceColumn.from_arrow(wide, decimal(12, 2), 64)
    after = xla_stats.encoding_stats()
    assert np.asarray(c7.data).dtype == np.int32   # narrow tier
    assert np.asarray(c12.data).dtype == np.int64
    assert after["decimal_scaled_int32_dispatches"] > \
        before["decimal_scaled_int32_dispatches"]
    assert after["decimal_scaled_int64_dispatches"] > \
        before["decimal_scaled_int64_dispatches"]
    assert c7.to_arrow(50).equals(narrow)
    assert c12.to_arrow(50).equals(wide)


def test_tier_counters_silent_when_knob_off():
    rng = np.random.default_rng(73)
    arr = pa.array([Decimal(int(v)).scaleb(-2)
                    for v in rng.integers(-10 ** 4, 10 ** 4, 20)],
                   type=pa.decimal128(7, 2))
    before = xla_stats.encoding_stats()
    c = DeviceColumn.from_arrow(arr, decimal(7, 2), 32)
    assert np.asarray(c.data).dtype == np.int64  # no narrow tier
    assert xla_stats.encoding_stats() == before
    assert c.to_arrow(20).equals(arr)


# -- partition-id parity -----------------------------------------------------

def test_pid_parity_host_decimal_vs_device_int64():
    """The host file shuffle hashes p<=18 decimals with the 'decimal'
    tid (long path); the device collective sees plain int64 unscaled
    values.  Both must route every row to the same reducer."""
    import jax.numpy as jnp

    from blaze_tpu.kernels import hashing as H
    from blaze_tpu.parallel.collective import partition_ids_for_keys
    rng = np.random.default_rng(83)
    vals = rng.integers(-10 ** 15, 10 ** 15, 256).astype(np.int64)
    valid = rng.random(256) > 0.1
    for p in (3, 8):
        host = H.spark_partition_ids([(vals, valid)], ["decimal"], p,
                                     xp=np)
        dev = partition_ids_for_keys(
            [(jnp.asarray(vals), jnp.asarray(valid))], p)
        assert np.array_equal(np.asarray(dev), np.asarray(host))


# -- planner admission + eviction accounting ---------------------------------

def _dec_out_schema(precision, scale):
    return {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "decimal", "precision": precision,
                               "scale": scale}, "nullable": True}]}


def test_exchange_device_spec_decimal_admission():
    from blaze_tpu.plan.planner import exchange_device_spec
    part = {"kind": "hash", "exprs": [{"kind": "column", "index": 0}],
            "num_partitions": 3}
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    try:
        before = xla_stats.encoding_stats()["host_evictions_decimal"]
        # knob off: the decimal column evicts the boundary, with reason
        assert exchange_device_spec(part, _dec_out_schema(12, 2)) is None
        mid = xla_stats.encoding_stats()["host_evictions_decimal"]
        assert mid == before + 1
        config.conf.set(config.ENCODING_DECIMAL_ENABLE.key, True)
        spec = exchange_device_spec(part, _dec_out_schema(12, 2))
        assert spec and spec["key_indices"] == [0]
        # wide decimals never take the int64 wire even with the knob on
        assert exchange_device_spec(part, _dec_out_schema(38, 10)) is None
        assert xla_stats.encoding_stats()["host_evictions_decimal"] == \
            mid + 1
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
        config.conf.unset(config.ENCODING_DECIMAL_ENABLE.key)


# -- end-to-end: scheduler + device exchange ---------------------------------

def _decimal_table(n=3000, seed=7, precision=12, scale=2, null_rate=0.08):
    rng = np.random.default_rng(seed)
    lim = 10 ** min(precision - 1, 6)
    vals = [Decimal(int(rng.integers(-lim, lim))).scaleb(-scale)
            if rng.random() > null_rate else None for _ in range(n)]
    return pa.table({
        "k": pa.array(rng.integers(0, 120, n), type=pa.int64()),
        "v": pa.array(vals, type=pa.decimal128(precision, scale))})


def _decimal_plan(tmp_path, t, precision, scale, tag="", n_reduce=3):
    paths = []
    half = t.num_rows // 2
    for i in range(2):
        p = str(tmp_path / f"dec{tag}-{i}.parquet")
        pq.write_table(t.slice(i * half, half), p)
        paths.append(p)
    schema = _dec_out_schema(precision, scale)
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}


def _sorted_df(tbl):
    return (tbl.to_pandas().sort_values("k", na_position="first")
            .reset_index(drop=True))


def _run_clean(tmp_path, plan, sub="clean"):
    """Reference run: encodings off, host file shuffle."""
    config.conf.set(config.SHUFFLE_DEVICE.key, "off")
    try:
        return _sorted_df(DagScheduler(
            work_dir=str(tmp_path / sub)).run_collect(plan))
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)


def test_decimal_exchange_device_resident_bit_identical(tmp_path,
                                                        staged_path):
    plan = _decimal_plan(tmp_path, _decimal_table(), 12, 2, tag="ex")
    clean = _run_clean(tmp_path, plan)
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    config.conf.set(config.ENCODING_DECIMAL_ENABLE.key, True)
    try:
        before = xla_stats.snapshot()
        got = _sorted_df(DagScheduler(
            work_dir=str(tmp_path / "dev")).run_collect(plan))
        d = xla_stats.delta(before)
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
        config.conf.unset(config.ENCODING_DECIMAL_ENABLE.key)
    assert d["shuffle_device_exchanges"] >= 1
    assert d["shuffle_device_fallbacks"] == 0
    assert d["decimal_scaled_int64_dispatches"] > 0
    assert got.equals(clean)


def test_decimal_int32_tier_e2e_bit_identical(tmp_path, staged_path):
    t = _decimal_table(precision=7, scale=2, seed=13)
    plan = _decimal_plan(tmp_path, t, 7, 2, tag="n32")
    clean = _run_clean(tmp_path, plan)
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    config.conf.set(config.ENCODING_DECIMAL_ENABLE.key, True)
    try:
        before = xla_stats.snapshot()
        got = _sorted_df(DagScheduler(
            work_dir=str(tmp_path / "dev32")).run_collect(plan))
        d = xla_stats.delta(before)
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
        config.conf.unset(config.ENCODING_DECIMAL_ENABLE.key)
    assert d["decimal_scaled_int32_dispatches"] > 0  # narrow scan tier
    assert d["shuffle_device_fallbacks"] == 0
    assert got.equals(clean)


def test_injected_collective_fault_falls_back_lossless(tmp_path,
                                                       staged_path):
    plan = _decimal_plan(tmp_path, _decimal_table(seed=19), 12, 2,
                         tag="ft")
    clean = _run_clean(tmp_path, plan)
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    config.conf.set(config.ENCODING_DECIMAL_ENABLE.key, True)
    try:
        before = xla_stats.snapshot()
        with faults.scoped(("device-collective", dict(p=1.0))):
            got = _sorted_df(DagScheduler(
                work_dir=str(tmp_path / "flt")).run_collect(plan))
        d = xla_stats.delta(before)
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
        config.conf.unset(config.ENCODING_DECIMAL_ENABLE.key)
    assert d["shuffle_device_fallbacks"] >= 1
    assert got.equals(clean)  # the file path reruns the stage losslessly


def test_decimal_zero_steady_state_recompiles(tmp_path, staged_path):
    plan = _decimal_plan(tmp_path, _decimal_table(seed=23), 12, 2,
                         tag="rc")
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    config.conf.set(config.ENCODING_DECIMAL_ENABLE.key, True)
    try:
        DagScheduler(work_dir=str(tmp_path / "r0")).run_collect(plan)
        before = xla_stats.snapshot()
        DagScheduler(work_dir=str(tmp_path / "r1")).run_collect(plan)
        d = xla_stats.delta(before)
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
        config.conf.unset(config.ENCODING_DECIMAL_ENABLE.key)
    assert d["shuffle_device_fallbacks"] == 0
    assert d["total_compiles"] == 0, \
        f"steady-state recompiles: {d['total_compiles']}"


def test_knob_off_eviction_accounting(tmp_path, staged_path):
    """With the decimal knob off the boundary stays on the host file
    shuffle — and the stats plane records WHY (decimal_column), which is
    what the advisor's host_eviction finding and the bench placement
    report key off."""
    plan = _decimal_plan(tmp_path, _decimal_table(seed=31), 12, 2,
                         tag="ev")
    clean = _run_clean(tmp_path, plan)
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    try:
        before = xla_stats.snapshot()
        got = _sorted_df(DagScheduler(
            work_dir=str(tmp_path / "off")).run_collect(plan))
        d = xla_stats.delta(before)
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)
    assert d["host_evictions_decimal"] >= 1
    assert d["shuffle_device_exchanges"] == 0
    assert got.equals(clean)  # disabled path is byte-identical
