"""Mesh-collective tests on the 8-virtual-device CPU mesh (the spark-local
analog for multi-chip paths, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blaze_tpu.parallel import (AggTable, distributed_grouped_agg, make_mesh,
                                merge_agg_tables, partial_agg_table,
                                shard_rows)


def test_partial_agg_table_fused():
    """The fused static-shape kernel matches a host groupby."""
    rng = np.random.default_rng(0)
    n = 4096
    keys = rng.integers(0, 50, n)
    vals = rng.random(n)
    valid = np.ones(n, dtype=bool)
    table = partial_agg_table(
        [(jnp.asarray(keys), jnp.ones(n, dtype=bool))],
        [("sum", jnp.asarray(vals), jnp.ones(n, dtype=bool)),
         ("count", None, None)],
        jnp.asarray(valid), num_slots=128)
    assert int(table.num_groups) == 50
    got = {}
    for i in range(128):
        if bool(table.slot_valid[i]):
            got[int(table.keys[0][i])] = (float(table.accs[0][i]),
                                          int(table.accs[1][i]))
    import pandas as pd
    want = pd.DataFrame({"k": keys, "v": vals}).groupby("k").agg(
        s=("v", "sum"), c=("v", "count"))
    assert len(got) == 50
    for k, row in want.iterrows():
        assert got[k][0] == pytest.approx(row.s)
        assert got[k][1] == row.c


def test_partial_agg_table_jits():
    """Must trace once (static shapes) and run under jit."""
    n = 1024
    f = jax.jit(lambda k, v, m: partial_agg_table(
        [(k, jnp.ones(n, dtype=bool))],
        [("sum", v, jnp.ones(n, dtype=bool))], m, num_slots=64))
    k = jnp.asarray(np.arange(n) % 10)
    v = jnp.ones(n)
    out = f(k, v, jnp.ones(n, dtype=bool))
    assert int(out.num_groups) == 10
    sums = np.asarray(out.accs[0])[np.asarray(out.slot_valid)]
    assert sums.sum() == pytest.approx(n)


def test_overflow_reported():
    n = 256
    table = partial_agg_table(
        [(jnp.asarray(np.arange(n)), jnp.ones(n, dtype=bool))],
        [("count", None, None)], jnp.ones(n, dtype=bool), num_slots=16)
    assert int(table.num_groups) == n  # host checks > num_slots -> fallback


def test_distributed_grouped_agg_end_to_end():
    """Full in-jit pipeline: per-device partial agg -> ICI all-to-all ->
    final merge, on an 8-device CPU mesh.  Oracle: pandas groupby."""
    mesh = make_mesh(8)
    rng = np.random.default_rng(1)
    n = 8 * 2048
    keys = rng.integers(0, 100, n).astype(np.int64)
    vals = rng.random(n)
    valid = np.ones(n, dtype=bool)

    step = distributed_grouped_agg(
        mesh, key_specs=1, agg_specs=["sum", "count"],
        num_slots=256, out_slots=512, merge_kinds=["sum", "count"])
    m, k, kv, v, vv = shard_rows(
        mesh, jnp.asarray(valid), jnp.asarray(keys),
        jnp.ones(n, dtype=bool), jnp.asarray(vals), jnp.ones(n, dtype=bool))
    out = step(m, k, kv, v, vv)

    slot_valid = np.asarray(out.slot_valid)
    got_keys = np.asarray(out.keys[0])[slot_valid]
    got_sums = np.asarray(out.accs[0])[slot_valid]
    got_counts = np.asarray(out.accs[1])[slot_valid]
    assert len(got_keys) == 100
    assert len(np.unique(got_keys)) == 100  # exchange really regrouped

    import pandas as pd
    want = pd.DataFrame({"k": keys, "v": vals}).groupby("k").agg(
        s=("v", "sum"), c=("v", "count"))
    gd = {int(k): (s, c) for k, s, c in zip(got_keys, got_sums, got_counts)}
    for k, row in want.iterrows():
        assert gd[int(k)][0] == pytest.approx(row.s)
        assert gd[int(k)][1] == row.c


def test_distributed_agg_with_nulls_and_filter():
    mesh = make_mesh(4)
    n = 4 * 512
    keys = np.arange(n) % 7
    vals = np.ones(n)
    vvalid = (np.arange(n) % 3) != 0          # some null values
    mask = np.arange(n) < (n // 2)            # filter half the rows

    step = distributed_grouped_agg(
        mesh, key_specs=1, agg_specs=["sum", "count"],
        num_slots=64, out_slots=64, merge_kinds=["sum", "count"])
    args = shard_rows(mesh, jnp.asarray(mask), jnp.asarray(keys),
                      jnp.ones(n, dtype=bool), jnp.asarray(vals),
                      jnp.asarray(vvalid))
    out = step(*args)
    slot_valid = np.asarray(out.slot_valid)
    # count spec is count(*): counts filtered-in rows regardless of value
    total_count = np.asarray(out.accs[1])[slot_valid].sum()
    assert total_count == int(mask.sum())
    # sum is null-aware: only valid values contribute
    total_sum = np.asarray(out.accs[0])[slot_valid].sum()
    assert total_sum == pytest.approx(float((vvalid & mask).sum()))
