"""Mesh-collective tests on the 8-virtual-device CPU mesh (the spark-local
analog for multi-chip paths, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blaze_tpu.parallel import (AggTable, distributed_grouped_agg, make_mesh,
                                merge_agg_tables, partial_agg_table,
                                shard_rows)


def test_partial_agg_table_fused():
    """The fused static-shape kernel matches a host groupby."""
    rng = np.random.default_rng(0)
    n = 4096
    keys = rng.integers(0, 50, n)
    vals = rng.random(n)
    valid = np.ones(n, dtype=bool)
    table = partial_agg_table(
        [(jnp.asarray(keys), jnp.ones(n, dtype=bool))],
        [("sum", jnp.asarray(vals), jnp.ones(n, dtype=bool)),
         ("count", None, None)],
        jnp.asarray(valid), num_slots=128)
    assert int(table.num_groups) == 50
    got = {}
    for i in range(128):
        if bool(table.slot_valid[i]):
            got[int(table.keys[0][i])] = (float(table.accs[0][i]),
                                          int(table.accs[1][i]))
    import pandas as pd
    want = pd.DataFrame({"k": keys, "v": vals}).groupby("k").agg(
        s=("v", "sum"), c=("v", "count"))
    assert len(got) == 50
    for k, row in want.iterrows():
        assert got[k][0] == pytest.approx(row.s)
        assert got[k][1] == row.c


def test_partial_agg_table_jits():
    """Must trace once (static shapes) and run under jit."""
    n = 1024
    f = jax.jit(lambda k, v, m: partial_agg_table(
        [(k, jnp.ones(n, dtype=bool))],
        [("sum", v, jnp.ones(n, dtype=bool))], m, num_slots=64))
    k = jnp.asarray(np.arange(n) % 10)
    v = jnp.ones(n)
    out = f(k, v, jnp.ones(n, dtype=bool))
    assert int(out.num_groups) == 10
    sums = np.asarray(out.accs[0])[np.asarray(out.slot_valid)]
    assert sums.sum() == pytest.approx(n)


def test_overflow_reported():
    n = 256
    table = partial_agg_table(
        [(jnp.asarray(np.arange(n)), jnp.ones(n, dtype=bool))],
        [("count", None, None)], jnp.ones(n, dtype=bool), num_slots=16)
    assert int(table.num_groups) == n  # host checks > num_slots -> fallback


def test_distributed_grouped_agg_end_to_end():
    """Full in-jit pipeline: per-device partial agg -> ICI all-to-all ->
    final merge, on an 8-device CPU mesh.  Oracle: pandas groupby."""
    mesh = make_mesh(8)
    rng = np.random.default_rng(1)
    n = 8 * 2048
    keys = rng.integers(0, 100, n).astype(np.int64)
    vals = rng.random(n)
    valid = np.ones(n, dtype=bool)

    step = distributed_grouped_agg(
        mesh, key_specs=1, agg_specs=["sum", "count"],
        num_slots=256, out_slots=512, merge_kinds=["sum", "count"])
    m, k, kv, v, vv = shard_rows(
        mesh, jnp.asarray(valid), jnp.asarray(keys),
        jnp.ones(n, dtype=bool), jnp.asarray(vals), jnp.ones(n, dtype=bool))
    out = step(m, k, kv, v, vv)

    slot_valid = np.asarray(out.slot_valid)
    got_keys = np.asarray(out.keys[0])[slot_valid]
    got_sums = np.asarray(out.accs[0])[slot_valid]
    got_counts = np.asarray(out.accs[1])[slot_valid]
    assert len(got_keys) == 100
    assert len(np.unique(got_keys)) == 100  # exchange really regrouped

    import pandas as pd
    want = pd.DataFrame({"k": keys, "v": vals}).groupby("k").agg(
        s=("v", "sum"), c=("v", "count"))
    gd = {int(k): (s, c) for k, s, c in zip(got_keys, got_sums, got_counts)}
    for k, row in want.iterrows():
        assert gd[int(k)][0] == pytest.approx(row.s)
        assert gd[int(k)][1] == row.c


def test_distributed_agg_with_nulls_and_filter():
    mesh = make_mesh(4)
    n = 4 * 512
    keys = np.arange(n) % 7
    vals = np.ones(n)
    vvalid = (np.arange(n) % 3) != 0          # some null values
    mask = np.arange(n) < (n // 2)            # filter half the rows

    step = distributed_grouped_agg(
        mesh, key_specs=1, agg_specs=["sum", "count"],
        num_slots=64, out_slots=64, merge_kinds=["sum", "count"])
    args = shard_rows(mesh, jnp.asarray(mask), jnp.asarray(keys),
                      jnp.ones(n, dtype=bool), jnp.asarray(vals),
                      jnp.asarray(vvalid))
    out = step(*args)
    slot_valid = np.asarray(out.slot_valid)
    # count spec is count(*): counts filtered-in rows regardless of value
    total_count = np.asarray(out.accs[1])[slot_valid].sum()
    assert total_count == int(mask.sum())
    # sum is null-aware: only valid values contribute
    total_sum = np.asarray(out.accs[0])[slot_valid].sum()
    assert total_sum == pytest.approx(float((vvalid & mask).sum()))


def test_dense_key_pack_unpack_roundtrip():
    from blaze_tpu.parallel.stage import pack_dense_keys, unpack_dense_keys
    n = 1000
    rng = np.random.default_rng(0)
    k1 = rng.integers(5, 50, n)
    k2 = rng.integers(0, 7, n)
    v1 = rng.random(n) < 0.9
    ranges = [(5, 49), (0, 6)]
    gid, total = pack_dense_keys(
        [(jnp.asarray(k1), jnp.asarray(v1)),
         (jnp.asarray(k2), jnp.ones(n, dtype=bool))], ranges)
    assert total == (49 - 5 + 2) * (6 - 0 + 2)
    assert int(jnp.max(gid)) < total
    # unpack every distinct gid and verify it matches the inputs
    ks = unpack_dense_keys(gid, ranges)
    got1, gv1 = np.asarray(ks[0][0]), np.asarray(ks[0][1])
    got2, _ = np.asarray(ks[1][0]), np.asarray(ks[1][1])
    assert (gv1 == v1).all()
    assert (got1[v1] == k1[v1]).all()
    assert (got2 == k2).all()


def test_dense_partial_agg_matches_sorted_path():
    from blaze_tpu.parallel.stage import (dense_partial_agg,
                                          pack_dense_keys,
                                          partial_agg_table)
    rng = np.random.default_rng(3)
    n = 4096
    keys = rng.integers(0, 100, n)
    vals = rng.random(n)
    mask = rng.random(n) < 0.7
    ones = jnp.ones(n, dtype=bool)
    gid, slots = pack_dense_keys([(jnp.asarray(keys), ones)], [(0, 99)])
    accs, avalid, occ = dense_partial_agg(
        gid, slots, [("sum", jnp.asarray(vals), None),
                     ("count", None, None),
                     ("min", jnp.asarray(vals), None),
                     ("max", jnp.asarray(vals), None)],
        jnp.asarray(mask))
    table = partial_agg_table(
        [(jnp.asarray(keys), ones)],
        [("sum", jnp.asarray(vals), ones), ("count", None, None),
         ("min", jnp.asarray(vals), ones), ("max", jnp.asarray(vals), ones)],
        jnp.asarray(mask), num_slots=128)
    sv = np.asarray(table.slot_valid)
    sorted_by_key = {int(k): (float(s), int(c), float(mn), float(mx))
                     for k, s, c, mn, mx in zip(
                         np.asarray(table.keys[0])[sv],
                         np.asarray(table.accs[0])[sv],
                         np.asarray(table.accs[1])[sv],
                         np.asarray(table.accs[2])[sv],
                         np.asarray(table.accs[3])[sv])}
    occ_np = np.asarray(occ)
    for slot in np.nonzero(occ_np)[0]:
        k = int(slot)  # identity packing with lo=0
        s = float(np.asarray(accs[0])[slot])
        c = int(np.asarray(accs[1])[slot])
        mn = float(np.asarray(accs[2])[slot])
        mx = float(np.asarray(accs[3])[slot])
        assert sorted_by_key[k] == (pytest.approx(s), c, pytest.approx(mn),
                                    pytest.approx(mx))
    assert occ_np.sum() == len(sorted_by_key)


@pytest.mark.dist
def test_distributed_broadcast_join_agg_eight_devices():
    """Broadcast join + agg in one SPMD program over the 8-device mesh:
    replicated build, sharded probe, psum-merged per-key aggregates."""
    import numpy as np
    import jax.numpy as jnp
    from blaze_tpu.parallel import (distributed_broadcast_join_agg,
                                    make_mesh, shard_rows)
    mesh = make_mesh(8)
    rng = np.random.default_rng(4)
    build = np.unique(rng.integers(0, 1000, 64))
    cap = len(build)
    n = 8 * 128
    probe = rng.integers(0, 1000, n)
    valid = rng.random(n) < 0.9
    vals = np.round(rng.random(n), 3)

    fn = distributed_broadcast_join_agg(mesh, cap)
    pk, pv, pw = shard_rows(mesh, jnp.asarray(probe),
                            jnp.asarray(valid), jnp.asarray(vals))
    sums, counts = fn(jnp.asarray(build), pk, pv, pw)
    sums, counts = np.asarray(sums), np.asarray(counts)

    # numpy oracle
    want_s = np.zeros(cap)
    want_c = np.zeros(cap, dtype=np.int64)
    pos = {k: i for i, k in enumerate(build)}
    for k, ok, v in zip(probe, valid, vals):
        if ok and k in pos:
            want_s[pos[k]] += v
            want_c[pos[k]] += 1
    assert np.array_equal(counts, want_c)
    np.testing.assert_allclose(sums, want_s, rtol=1e-12)
