"""Flink front-end tests: CompiledPlan JSON -> engine IR -> execution
via the mock Kafka source (ref auron-flink-planner converters +
AuronOperatorFusionProcessor; kafka_mock_scan_exec.rs test pattern)."""

import json

import pyarrow as pa
import pytest

from blaze_tpu.convert import ConversionError
from blaze_tpu.convert.flink import (convert_flink_plan, convert_rex,
                                     type_from_flink)
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _compiled_plan(mock_rows, projection, condition=None):
    """A Flink `COMPILE PLAN`-shaped exec graph: kafka source -> calc ->
    sink (the exact fusion target of AuronOperatorFusionProcessor)."""
    return {
        "flinkVersion": "1.18",
        "nodes": [
            {"id": 1,
             "type": "stream-exec-table-source-scan_1",
             "scanTableSource": {"table": {"resolvedTable": {
                 "schema": {"columns": [
                     {"name": "user_id", "dataType": "BIGINT"},
                     {"name": "amount", "dataType": "DOUBLE"},
                     {"name": "category", "dataType": "VARCHAR(2147483647)"},
                 ]},
                 "options": {"connector": "kafka", "topic": "orders",
                             "format": "json",
                             "__mock_data__": json.dumps(mock_rows)}}}}},
            {"id": 2, "type": "stream-exec-calc_2",
             "projection": projection, "condition": condition},
            {"id": 3, "type": "stream-exec-sink_3"},
        ],
        "edges": [{"source": 1, "target": 2},
                  {"source": 2, "target": 3}],
    }


def _ref(i, t):
    return {"kind": "INPUT_REF", "inputIndex": i, "type": t}


def _lit(v, t):
    return {"kind": "LITERAL", "value": v, "type": t}


def _call(op, operands, t="BOOLEAN"):
    return {"kind": "CALL", "internalName": f"${op}$1",
            "operands": operands, "type": t}


ROWS = [
    {"user_id": 1, "amount": 10.0, "category": "a"},
    {"user_id": 2, "amount": 55.5, "category": "b"},
    {"user_id": 3, "amount": 7.25, "category": "a"},
    {"user_id": 4, "amount": 99.0, "category": "c"},
]


def test_kafka_calc_fusion_end_to_end():
    plan_json = _compiled_plan(
        ROWS,
        projection=[_ref(0, "BIGINT"),
                    _call("*", [_ref(1, "DOUBLE"),
                                _lit(2.0, "DOUBLE")], "DOUBLE"),
                    _call("UPPER", [_ref(2, "VARCHAR(2147483647)")],
                          "VARCHAR(2147483647)")],
        condition=_call("AND", [
            _call(">", [_ref(1, "DOUBLE"), _lit(8.0, "DOUBLE")]),
            _call("IS NOT NULL", [_ref(0, "BIGINT")])]))
    ir = convert_flink_plan(plan_json)
    plan = create_plan(ir)
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in plan.execute(0)]).to_pandas()
    want = [(1, 20.0, "A"), (2, 111.0, "B"), (4, 198.0, "C")]
    got = sorted(zip(out.iloc[:, 0], out.iloc[:, 1], out.iloc[:, 2]))
    assert got == want


def test_rex_vocabulary():
    assert convert_rex(_call("<>", [_ref(0, "INT"), _lit(1, "INT")])) \
        == {"kind": "not", "child": {"kind": "binary", "op": "==",
                                     "l": {"kind": "column", "index": 0},
                                     "r": {"kind": "literal", "value": 1,
                                           "type": {"id": "int32"}}}}
    cast = convert_rex({"kind": "CALL", "internalName": "$CAST$1",
                        "operands": [_ref(0, "INT")], "type": "BIGINT"})
    assert cast == {"kind": "cast",
                    "child": {"kind": "column", "index": 0},
                    "type": {"id": "int64"}}
    case = convert_rex(_call("CASE", [
        _call(">", [_ref(0, "INT"), _lit(0, "INT")]),
        _lit(1, "INT"), _lit(2, "INT")], "INT"))
    assert case["kind"] == "case" and "else" in case
    with pytest.raises(ConversionError, match="unsupported operator"):
        convert_rex(_call("TUMBLE", [_ref(0, "INT")]))


def test_types():
    assert type_from_flink("DECIMAL(10, 2)") == \
        {"id": "decimal", "precision": 10, "scale": 2}
    assert type_from_flink("TIMESTAMP(3)") == {"id": "timestamp_us"}
    assert type_from_flink("INT NOT NULL") == {"id": "int32"}
    with pytest.raises(ConversionError):
        type_from_flink("INTERVAL DAY")


def test_non_kafka_connector_rejected():
    plan_json = _compiled_plan(ROWS, projection=[_ref(0, "BIGINT")])
    opts = plan_json["nodes"][0]["scanTableSource"]["table"][
        "resolvedTable"]["options"]
    opts["connector"] = "filesystem"
    with pytest.raises(ConversionError, match="unsupported connector"):
        convert_flink_plan(plan_json)


def test_micro_batch_runtime_operator():
    """The FlinkAuronCalcOperator analog (VERDICT r3 #8): a converted
    COMPILE-PLAN executes END-TO-END through protobuf TaskDefinition
    bytes + NativeExecutionRuntime as a micro-batch loop, with kafka
    offsets advancing across batches (checkpoint/restore state)."""
    from blaze_tpu.convert.flink_runtime import FlinkMicroBatchOperator
    from blaze_tpu.ops.kafka import KafkaRecord

    plan_json = _compiled_plan(
        ROWS,  # inline mock data is ignored by the runtime operator
        projection=[_ref(0, "BIGINT"),
                    _call("*", [_ref(1, "DOUBLE"),
                                _lit(2.0, "DOUBLE")], "DOUBLE")],
        condition=_call(">", [_ref(1, "DOUBLE"), _lit(8.0, "DOUBLE")]))
    op = FlinkMicroBatchOperator(plan_json)

    def recs(rows, base):
        return [[KafkaRecord(value=json.dumps(r).encode(),
                             offset=base + i)
                 for i, r in enumerate(rows)]]

    # micro-batch 1: two records, one passes the filter
    out1 = op.run_micro_batch(recs(ROWS[:2], 0))
    got1 = [tuple(r) for rb in out1
            for r in zip(*[c.to_pylist() for c in rb.columns])]
    assert got1 == [(1, 20.0), (2, 111.0)]
    assert op.offsets[0] == 2

    # checkpoint, then micro-batch 2
    ckpt = op.snapshot_state()
    out2 = op.run_micro_batch(recs(ROWS[2:], 2))
    got2 = sorted(tuple(r) for rb in out2
                  for r in zip(*[c.to_pylist() for c in rb.columns]))
    assert got2 == [(4, 198.0)]  # amount 7.25 filtered out
    assert op.offsets[0] == 4 and op.batches_run == 2

    # restore rolls offsets back (at-least-once replay contract)
    op.restore_state(ckpt)
    assert op.offsets[0] == 2


def test_group_aggregate_node_converts_and_runs():
    """stream-exec-group-aggregate -> hash_agg through the AggregateCall
    converter registry (FlinkAggCallConverter analog)."""
    import pyarrow as pa
    from blaze_tpu.bridge.resource import put_resource
    from blaze_tpu.plan import create_plan
    t = pa.table({"k": pa.array([1, 1, 2]), "v": pa.array([10.0, 5.0, 2.0])})
    put_resource("flink://agg-src", t)
    plan_json = {
        "nodes": [
            {"id": 1, "type": "stream-exec-table-source-scan_1",
             "scanTableSource": {"table": {
                 "identifier": "`default`.`db`.`t`",
                 "resolvedTable": {"schema": {"columns": [
                     {"name": "k", "dataType": "BIGINT"},
                     {"name": "v", "dataType": "DOUBLE"}]},
                     "options": {"connector": "values",
                                 "resource-id": "flink://agg-src"}}}}},
            {"id": 2, "type": "stream-exec-group-aggregate_1",
             "grouping": [0],
             "aggCalls": [{"name": "s", "internalName": "$SUM$1",
                           "argList": [1]},
                          {"name": "c", "internalName": "$COUNT$1",
                           "argList": []}]},
            {"id": 3, "type": "stream-exec-sink_1"}],
        "edges": [{"source": 1, "target": 2}, {"source": 2, "target": 3}]}
    ir = convert_flink_plan(plan_json)
    assert ir["kind"] == "hash_agg"
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in create_plan(ir).execute(0)])
    got = {r[0]: (r[1], r[2]) for r in
           zip(*[c.to_pylist() for c in out.columns])}
    assert got == {1: (15.0, 2), 2: (2.0, 1)}


def test_agg_converter_registry_rejects_duplicates():
    from blaze_tpu.convert import flink as F
    F.register_agg_converter("MYAGG", lambda c: {"fn": "sum", "args": []})
    try:
        with pytest.raises(ValueError, match="already registered"):
            F.register_agg_converter("MYAGG", lambda c: None)
        # custom converter wins over built-ins
        spec = F.convert_agg_call({"internalName": "$MYAGG$1"})
        assert spec == {"fn": "sum", "args": []}
    finally:
        F._AGG_CONVERTERS.pop("MYAGG", None)


def test_distinct_aggregate_falls_back():
    from blaze_tpu.convert import flink as F
    from blaze_tpu.convert.flink import ConversionError
    with pytest.raises(ConversionError, match="DISTINCT"):
        F.convert_agg_call({"internalName": "$SUM$1", "argList": [0],
                            "distinct": True})


def test_two_phase_local_global_aggregate():
    """TWO_PHASE agg: local -> partial acc columns, global -> final
    rebinding them positionally (the engine's partial/final split)."""
    import pyarrow as pa
    from blaze_tpu.bridge.resource import put_resource
    from blaze_tpu.plan import create_plan
    t = pa.table({"k": pa.array([1, 1, 2, 2]),
                  "v": pa.array([10.0, 5.0, 2.0, 1.0])})
    put_resource("flink://2p-src", t)
    src = {"id": 1, "type": "stream-exec-table-source-scan_1",
           "scanTableSource": {"table": {
               "identifier": "`d`.`db`.`t`",
               "resolvedTable": {"schema": {"columns": [
                   {"name": "k", "dataType": "BIGINT"},
                   {"name": "v", "dataType": "DOUBLE"}]},
                   "options": {"connector": "values",
                               "resource-id": "flink://2p-src"}}}}}
    calls = [{"name": "s", "internalName": "$SUM$1", "argList": [1]},
             {"name": "a", "internalName": "$AVG$1", "argList": [1]}]
    plan_json = {
        "nodes": [src,
                  {"id": 2, "type": "stream-exec-local-group-aggregate_1",
                   "grouping": [0], "aggCalls": calls},
                  {"id": 3, "type": "stream-exec-exchange_1"},
                  {"id": 4, "type": "stream-exec-global-group-aggregate_1",
                   "grouping": [0], "aggCalls": calls},
                  {"id": 5, "type": "stream-exec-sink_1"}],
        "edges": [{"source": 1, "target": 2}, {"source": 2, "target": 3},
                  {"source": 3, "target": 4}, {"source": 4, "target": 5}]}
    ir = convert_flink_plan(plan_json)
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in create_plan(ir).execute(0)])
    got = {r[0]: (r[1], r[2]) for r in
           zip(*[c.to_pylist() for c in out.columns])}
    assert got == {1: (15.0, 7.5), 2: (3.0, 1.5)}
