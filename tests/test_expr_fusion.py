"""Whole-stage expression compilation: fused-vs-eager parity, fallback
rules, the process-wide program cache, constant folding, and the planner
Filter->Project collapse (ISSUE 3)."""

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.bridge import xla_stats
from blaze_tpu.exprs import (BinaryExpr, CachedExprsEvaluator, Cast,
                             Coalesce, FusedExprsEvaluator, If, InList,
                             IsNull, Like, Literal, Not, col,
                             fold_constants, fold_node, fused_filter,
                             is_traceable, lit)
from blaze_tpu.exprs.program import (clear_program_cache, get_program,
                                     program_cache_info)
from blaze_tpu.exprs.special import Rand
from blaze_tpu.ops import (FilterExec, FilterProjectExec, MemoryScanExec,
                           ProjectExec)
from blaze_tpu.plan.planner import collapse_filter_project
from blaze_tpu.schema import DataType, Field, Schema, TypeId


@pytest.fixture(autouse=True)
def _fresh_cache():
    clear_program_cache()
    yield
    clear_program_cache()


def _table(n=500, seed=0, nulls=False):
    rng = np.random.default_rng(seed)
    a = rng.integers(-50, 50, n)
    b = rng.random(n) * 100 - 50
    c = rng.integers(0, 1000, n).astype(np.int32)
    if not nulls:
        return pa.table({"a": pa.array(a), "b": pa.array(b),
                         "c": pa.array(c)})
    mask = rng.random(n) < 0.25
    return pa.table({
        "a": pa.array([None if m else int(v) for m, v in zip(mask, a)],
                      pa.int64()),
        "b": pa.array([None if m else float(v)
                       for m, v in zip(np.roll(mask, 7), b)], pa.float64()),
        "c": pa.array(c),
    })


def _out_schema(projections, in_schema):
    return Schema([Field(f"o{i}", e.data_type(in_schema))
                   for i, e in enumerate(projections)])


def _parity_fp(tbl, filters, projections):
    """Run the chain fused and eager; both must be row-identical."""
    batch = ColumnBatch.from_arrow(tbl)
    in_schema = batch.schema
    out_schema = _out_schema(projections, in_schema)
    fused = FusedExprsEvaluator(filters=filters, projections=projections,
                                in_schema=in_schema)
    eager = CachedExprsEvaluator(filters=filters, projections=projections)
    got = fused.filter_project(batch, out_schema).compact().to_arrow()
    want = eager.filter_project(batch, out_schema).compact().to_arrow()
    assert got.num_rows == want.num_rows
    for i in range(want.num_columns):
        assert got.column(i).equals(want.column(i)), \
            f"col {i}: {got.column(i)} != {want.column(i)}"
    return got


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_filter_parity_3vl_nulls():
    # NULL > 5 is NULL -> row excluded; OR keeps TRUE when one side NULL
    tbl = _table(nulls=True)
    pred = BinaryExpr("or",
                      BinaryExpr(">", col(0), lit(5)),
                      BinaryExpr("and",
                                 BinaryExpr("<", col(1), lit(-10.0)),
                                 Not(IsNull(col(0)))))
    _parity_fp(tbl, [pred], [col(0), col(1)])


def test_project_parity_dtype_promotion():
    # int32 + int64 and int64 * float64 promotions inside one program
    tbl = _table()
    projs = [BinaryExpr("+", col(2), col(0)),
             BinaryExpr("*", col(0), col(1)),
             Cast(col(2), DataType(TypeId.FLOAT64))]
    _parity_fp(tbl, [], projs)


def test_filter_project_parity_conditionals():
    tbl = _table(nulls=True)
    pred = InList(col(2), tuple(range(0, 1000, 3)))
    projs = [If(BinaryExpr(">", col(0), lit(0)), col(1), lit(0.0)),
             Coalesce((col(0), lit(-1)))]
    got = _parity_fp(tbl, [pred], projs)
    assert got.num_rows > 0  # the parity must not be vacuous


def test_empty_batch():
    tbl = pa.table({"a": pa.array([], pa.int64()),
                    "b": pa.array([], pa.float64()),
                    "c": pa.array([], pa.int32())})
    got = _parity_fp(tbl, [BinaryExpr(">", col(0), lit(0))], [col(1)])
    assert got.num_rows == 0


def test_bucket_boundary_sizes():
    # sizes straddling capacity rungs: pad-to-bucket must not leak
    # padding rows into results, and resizing must not change rows
    for n in (1, 127, 128, 129, 500):
        tbl = _table(n=n, seed=n)
        _parity_fp(tbl, [BinaryExpr(">=", col(0), lit(0))],
                   [BinaryExpr("+", col(1), lit(1.0))])


# ---------------------------------------------------------------------------
# fallback rules
# ---------------------------------------------------------------------------

def test_string_predicate_falls_back_eager():
    tbl = pa.table({"s": pa.array([f"id_{i % 7}" for i in range(64)])})
    batch = ColumnBatch.from_arrow(tbl)
    pred = Like(col(0), "id_1%")
    assert not is_traceable(pred, batch.schema)
    before = xla_stats.expr_stats()["expr_eager_batches"]
    ev = FusedExprsEvaluator(filters=[pred], in_schema=batch.schema)
    out = ev.filter(batch)
    assert xla_stats.expr_stats()["expr_eager_batches"] == before + 1
    assert out.compact().to_arrow().num_rows == \
        sum(1 for i in range(64) if i % 7 == 1)


def test_literal_only_filter_stays_eager():
    # no column refs -> the jit would have no array argument; stays eager
    tbl = _table(64)
    batch = ColumnBatch.from_arrow(tbl)
    ev = FusedExprsEvaluator(filters=[BinaryExpr(">", lit(2), lit(1))],
                             in_schema=batch.schema)
    assert ev._filter_prog is None and ev._fp_prog is None
    assert ev.filter(batch).selected_count() == 64


def test_fuse_config_off():
    tbl = _table(64)
    batch = ColumnBatch.from_arrow(tbl)
    with config.scoped(**{"auron.tpu.expr.fuse": False}):
        ev = FusedExprsEvaluator(filters=[BinaryExpr(">", col(0), lit(0))],
                                 in_schema=batch.schema)
        assert ev._filter_prog is None
        out = ev.filter(batch)
    want = CachedExprsEvaluator(
        filters=[BinaryExpr(">", col(0), lit(0))]).filter(batch)
    assert out.selected_count() == want.selected_count()


def test_mixed_chain_fuses_filter_only():
    # traceable filter + host-only projection: fused mask, eager project
    tbl = pa.table({"a": pa.array(range(100), pa.int64()),
                    "s": pa.array([f"x{i}" for i in range(100)])})
    batch = ColumnBatch.from_arrow(tbl)
    filters = [BinaryExpr(">", col(0), lit(49))]
    projs = [col(0), col(1)]
    ev = FusedExprsEvaluator(filters=filters, projections=projs,
                             in_schema=batch.schema)
    assert ev._fp_prog is None and ev._filter_prog is not None
    out_schema = _out_schema(projs, batch.schema)
    got = ev.filter_project(batch, out_schema).compact().to_arrow()
    assert got.column(1).to_pylist() == [f"x{i}" for i in range(50, 100)]


# ---------------------------------------------------------------------------
# the program cache
# ---------------------------------------------------------------------------

def test_cache_shared_across_evaluator_instances():
    tbl = _table(64)
    sch = ColumnBatch.from_arrow(tbl).schema
    filters = [BinaryExpr(">", col(0), lit(0))]
    before = xla_stats.expr_stats()
    ev1 = FusedExprsEvaluator(filters=filters, in_schema=sch)
    ev2 = FusedExprsEvaluator(filters=filters, in_schema=sch)
    after = xla_stats.expr_stats()
    assert after["expr_programs_built"] - before["expr_programs_built"] == 1
    assert after["expr_program_cache_hits"] - \
        before["expr_program_cache_hits"] == 1
    assert ev1._filter_prog is ev2._filter_prog


def test_cache_lru_eviction():
    sch = Schema([Field("a", DataType(TypeId.INT64))])
    before = xla_stats.expr_stats()["expr_program_evictions"]
    with config.scoped(**{"auron.tpu.expr.cache.size": 2}):
        for k in range(4):
            get_program("filter", [BinaryExpr(">", col(0), lit(k))], (), sch)
    assert program_cache_info()["size"] == 2
    assert xla_stats.expr_stats()["expr_program_evictions"] == before + 2


def test_fingerprint_distinguishes_dtype_signature():
    f64 = Schema([Field("a", DataType(TypeId.FLOAT64))])
    i64 = Schema([Field("a", DataType(TypeId.INT64))])
    filters = [BinaryExpr(">", col(0), lit(0))]
    p1 = get_program("filter", filters, (), f64)
    p2 = get_program("filter", filters, (), i64)
    assert p1 is not p2 and p1.name != p2.name


# ---------------------------------------------------------------------------
# scan-embedded filtering
# ---------------------------------------------------------------------------

def test_fused_filter_for_scan():
    tbl = _table(200, seed=3)
    batch = ColumnBatch.from_arrow(tbl)
    pred = BinaryExpr("and", BinaryExpr(">", col(0), lit(0)),
                      BinaryExpr("<", col(1), lit(25.0)))
    apply = fused_filter([pred], batch.schema)
    assert apply is not None
    got = apply(batch).compact().to_arrow()
    want = CachedExprsEvaluator(filters=[pred]).filter(
        batch).compact().to_arrow()
    assert got.num_rows == want.num_rows
    assert got.column(0).equals(want.column(0))
    # host-only predicate: scan must decline and defer to the operator
    stbl = pa.table({"s": pa.array(["a", "b"])})
    sbatch = ColumnBatch.from_arrow(stbl)
    assert fused_filter([Like(col(0), "a%")], sbatch.schema) is None


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

def test_fold_constants_arithmetic():
    sch = Schema([Field("a", DataType(TypeId.INT64))])
    e = BinaryExpr(">", col(0),
                   BinaryExpr("*", lit(5), BinaryExpr("+", lit(4), lit(6))))
    folded = fold_constants(e, sch)
    assert isinstance(folded.right, Literal) and folded.right.value == 50
    assert isinstance(folded.left, type(col(0)))


def test_fold_preserves_null_semantics():
    sch = Schema([Field("a", DataType(TypeId.INT64))])
    e = BinaryExpr("+", lit(1), Literal(None, DataType(TypeId.INT64)))
    folded = fold_node(e, sch)
    assert isinstance(folded, Literal) and folded.value is None


def test_fold_config_off():
    sch = Schema([Field("a", DataType(TypeId.INT64))])
    e = BinaryExpr("+", lit(1), lit(2))
    with config.scoped(**{"auron.tpu.expr.constFold": False}):
        assert not isinstance(fold_node(e, sch), Literal)
    assert fold_node(e, sch).value == 3


# ---------------------------------------------------------------------------
# planner collapse
# ---------------------------------------------------------------------------

def _scan(tbl, **kw):
    return MemoryScanExec.from_arrow(tbl, **kw)


def test_collapse_filter_then_project():
    tbl = _table(300)
    plan = ProjectExec(
        FilterExec(_scan(tbl), [BinaryExpr(">", col(0), lit(0))]),
        [BinaryExpr("*", col(1), lit(2.0))], ["b2"])
    want = plan.execute_collect().to_arrow()
    collapsed = collapse_filter_project(plan)
    assert isinstance(collapsed, FilterProjectExec)
    got = collapsed.execute_collect().to_arrow()
    assert got.num_rows == want.num_rows
    assert np.allclose(np.sort(got.column(0).to_numpy()),
                       np.sort(want.column(0).to_numpy()))


def test_collapse_project_project():
    tbl = _table(300)
    inner = ProjectExec(_scan(tbl),
                        [BinaryExpr("+", col(0), col(0)), col(1)],
                        ["a2", "b"])
    plan = ProjectExec(inner, [BinaryExpr("*", col(0), lit(3))], ["a6"])
    want = plan.execute_collect().to_arrow()
    collapsed = collapse_filter_project(plan)
    assert isinstance(collapsed, ProjectExec)
    assert not isinstance(collapsed.children[0], ProjectExec)
    got = collapsed.execute_collect().to_arrow()
    assert got.column(0).to_pylist() == want.column(0).to_pylist()


def test_collapse_bails_on_stateful_inner():
    # Rand duplicated through substitution would re-roll: must not merge
    tbl = _table(100)
    inner = ProjectExec(_scan(tbl), [Rand(seed=7), col(1)], ["r", "b"])
    plan = ProjectExec(inner, [BinaryExpr("+", col(0), col(0))], ["r2"])
    collapsed = collapse_filter_project(plan)
    assert isinstance(collapsed.children[0], ProjectExec)


def test_collapse_config_off():
    tbl = _table(100)
    plan = ProjectExec(
        FilterExec(_scan(tbl), [BinaryExpr(">", col(0), lit(0))]),
        [col(1)], ["b"])
    with config.scoped(**{"auron.tpu.plan.collapseFilterProject": False}):
        assert collapse_filter_project(plan) is plan
