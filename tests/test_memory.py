"""Memory manager + spill tier tests (ref auron-memmgr unit behavior)."""

import io

import numpy as np
import pyarrow as pa

from blaze_tpu.memory import (FileSpill, HostMemSpill, MemConsumer, MemManager)
from blaze_tpu.shuffle.ipc import (IpcCompressionReader, IpcCompressionWriter,
                                   read_batches_from_bytes,
                                   write_batches_to_bytes)


class FakeConsumer(MemConsumer):
    def __init__(self, name):
        super().__init__(name)
        self.spill_calls = 0

    def spill(self):
        self.spill_calls += 1
        released = self._mem_used
        self._mem_used = 0
        return released


def test_mem_manager_spills_biggest_on_overflow():
    mm = MemManager(1000)
    a, b = FakeConsumer("a"), FakeConsumer("b")
    a.set_spillable(mm)
    b.set_spillable(mm)
    a.update_mem_used(400)
    assert a.spill_calls == 0
    b.update_mem_used(700)  # total 1100 > 1000 -> biggest (b) spills
    assert b.spill_calls == 1
    assert mm.mem_used == 400
    a.unregister()
    b.unregister()


def test_mem_manager_fair_share_cap():
    mm = MemManager(1000)
    a, b = FakeConsumer("a"), FakeConsumer("b")
    a.set_spillable(mm)
    b.set_spillable(mm)
    # one consumer hogging >2x fair share (cap=500) spills even under budget
    a.update_mem_used(999)
    assert a.spill_calls == 0  # 999 < 1000 total, and 999 <= 2*500=1000
    a.update_mem_used(1001)
    assert a.spill_calls == 1
    a.unregister()
    b.unregister()


def _batches():
    return [pa.record_batch({"x": pa.array(range(100)),
                             "s": pa.array([f"v{i}" for i in range(100)])}),
            pa.record_batch({"x": pa.array(range(100, 150)),
                             "s": pa.array([f"v{i}" for i in range(50)])})]


def test_ipc_roundtrip_bytes():
    data = write_batches_to_bytes(_batches())
    out = list(read_batches_from_bytes(data))
    got = pa.Table.from_batches(out)
    want = pa.Table.from_batches(_batches())
    assert got.equals(want)


def test_ipc_multi_frame():
    sink = io.BytesIO()
    w = IpcCompressionWriter(sink, target_frame_bytes=1)  # frame per batch
    for b in _batches():
        w.write_batch(b)
    w.finish()
    assert w.frames_written == 2
    sink.seek(0)
    out = list(IpcCompressionReader(sink).read_batches())
    assert sum(b.num_rows for b in out) == 150


def test_host_and_file_spill_roundtrip():
    for spill in (HostMemSpill(), FileSpill()):
        spill.write_batches(iter(_batches()))
        assert spill.stored_bytes > 0
        got = pa.Table.from_batches(list(spill.read_batches()))
        assert got.equals(pa.Table.from_batches(_batches()))
        spill.release()
