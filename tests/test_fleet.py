"""Replicated serving fleet (fleet/): hardened stream framing,
fingerprint-affine rendezvous routing, replica-death survival
(`replica-crash`, `replica-hang`, `socket-torn-frame` chaos sites),
graceful drain, the /fleet health surface, and per-replica history
rollups."""

import io
import json
import socket
import time
import urllib.request

import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import history, tracing, xla_stats
from blaze_tpu.fleet import (FleetQueryLost, FleetRouter, ReplicaServer,
                             fleet_health)
from blaze_tpu.fleet.router import FleetQueryFailed
from blaze_tpu.memory import MemManager
from blaze_tpu.shuffle.ipc import (CODEC_RAW, FrameTransportClosed,
                                   pack_control_frame, recv_control_frame,
                                   recv_exact, sock_recv_frame,
                                   sock_send_frame)

from tests.test_serving import _two_stage_plan

_FLEET_KNOBS = (config.FLEET_HEARTBEAT_MS, config.FLEET_LIVENESS_MS,
                config.FLEET_PROBE_BACKOFF_MS, config.FLEET_RETRIES,
                config.FLEET_HEDGE_ENABLE, config.FLEET_REPLICA_ID)


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    try:
        yield
    finally:
        faults.clear()
        for opt in _FLEET_KNOBS:
            config.conf.unset(opt.key)
        tracing.stop_tracing()
        with tracing._lock:
            tracing._spans.clear()
        tracing.reset_conf_probe()
        MemManager.init(4 << 30)


@pytest.fixture
def fleet(request):
    """Three in-process replicas + a router with test-speed heartbeats.
    Yields (router, replicas)."""
    config.conf.set(config.FLEET_HEARTBEAT_MS.key, 50)
    config.conf.set(config.FLEET_LIVENESS_MS.key, 400)
    config.conf.set(config.FLEET_PROBE_BACKOFF_MS.key, 50)
    config.conf.set(config.FLEET_RETRIES.key, 3)
    replicas = [ReplicaServer(f"r{i}").start() for i in range(3)]
    router = FleetRouter([(r.replica_id, r.addr) for r in replicas])
    try:
        yield router, replicas
    finally:
        router.close()
        for r in replicas:
            r.kill()


def _frame(t):
    import pandas as pd
    return t.to_pandas() if t.num_rows else pd.DataFrame(
        {n: [] for n in t.schema.names})


# -- stream framing (shuffle/ipc.py hardening) -------------------------------

def test_recv_exact_loops_on_short_reads():
    chunks = [b"ab", b"c", b"de"]
    assert recv_exact(lambda n: chunks.pop(0), 5) == b"abcde"


def test_recv_exact_clean_eof_at_boundary_is_none():
    assert recv_exact(lambda n: b"", 4) is None


def test_recv_exact_mid_frame_eof_is_retryable_transport_loss():
    """EOF with bytes already consumed is a dead peer, not corruption:
    FrameTransportClosed (a ConnectionError ⇒ retryable), never a
    checksum error."""
    chunks = [b"ab"]
    with pytest.raises(FrameTransportClosed):
        recv_exact(lambda n: chunks.pop(0) if chunks else b"", 4)
    assert faults.classify_exception(FrameTransportClosed()) == "retryable"


def test_recv_control_frame_roundtrip_one_byte_reads():
    frame = pack_control_frame(b"payload-bytes", CODEC_RAW)
    buf = io.BytesIO(frame)
    assert recv_control_frame(lambda n: buf.read(1)) == b"payload-bytes"
    assert recv_control_frame(lambda n: buf.read(1)) is None  # clean EOF


def test_recv_control_frame_truncated_is_transport_loss():
    frame = pack_control_frame(b"payload-bytes", CODEC_RAW)
    buf = io.BytesIO(frame[:len(frame) // 2])
    with pytest.raises(FrameTransportClosed):
        recv_control_frame(buf.read)


def test_socket_torn_frame_fault_site():
    """The `socket-torn-frame` chaos site: the sender dies mid-frame and
    the receiver classifies the loss as retryable peer death."""
    a, b = socket.socketpair()
    try:
        with faults.scoped(("socket-torn-frame", dict(at=(1,)))):
            with pytest.raises(FrameTransportClosed):
                sock_send_frame(a, b"x" * 1024)
        b.settimeout(5.0)
        with pytest.raises(FrameTransportClosed):
            sock_recv_frame(b)
    finally:
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass


# -- rendezvous routing ------------------------------------------------------

def test_rendezvous_ranking_is_deterministic_and_spreads(fleet):
    router, _ = fleet
    fps = [f"fingerprint-{i}" for i in range(16)]
    first = {fp: router._rank(fp)[0].replica_id for fp in fps}
    # deterministic: re-ranking agrees with itself
    assert first == {fp: router._rank(fp)[0].replica_id for fp in fps}
    # and different fingerprints spread over the fleet
    assert len(set(first.values())) >= 2


def test_repeat_queries_are_affine(fleet, tmp_path):
    router, replicas = fleet
    plan = _two_stage_plan(tmp_path, n=2000)
    a = router.execute(plan)
    b = router.execute(plan)
    assert _frame(a).equals(_frame(b))
    h = router.health()
    assert h["affinity_hit_rate"] == 1.0
    served = [r for r in h["replicas"] if r["queries_routed"]]
    assert len(served) == 1  # both landed on the cache-warm replica
    assert served[0]["queries_done"] == 2


def test_two_routers_agree_on_affinity(fleet, tmp_path):
    """Any router instance computes the same fingerprint→replica map —
    affinity needs no shared state between routers."""
    router, replicas = fleet
    other = FleetRouter([(r.replica_id, r.addr) for r in replicas],
                        heartbeat=False)
    try:
        plan = _two_stage_plan(tmp_path, n=2000, tag="-b")
        fp = router.fingerprint(plan)
        assert (router._rank(fp)[0].replica_id
                == other._rank(fp)[0].replica_id)
    finally:
        other.close()


# -- replica death -----------------------------------------------------------

def test_replica_crash_reroutes_and_retries(fleet, tmp_path):
    """The `replica-crash` site: the affine replica dies holding the
    query; the router marks it down and the query retries end-to-end on
    a sibling — same bytes out, zero lost queries."""
    router, replicas = fleet
    plan = _two_stage_plan(tmp_path, n=2000, tag="-c")
    base = _frame(router.execute(plan))
    before = xla_stats.fleet_stats()
    with faults.scoped(("replica-crash", dict(at=(1,)))):
        got = _frame(router.execute(plan))
    assert got.equals(base)
    h = router.health()
    assert h["replicas_down"] == 1
    after = xla_stats.fleet_stats()
    assert after["fleet_reroutes"] > before["fleet_reroutes"]
    assert after["fleet_queries_lost"] == before["fleet_queries_lost"]


def test_killed_replica_is_probed_back_up(fleet, tmp_path):
    router, replicas = fleet
    tracing.start_tracing()
    plan = _two_stage_plan(tmp_path, n=2000, tag="-k")
    router.execute(plan)
    victim = next(r for r in router.health()["replicas"]
                  if r["queries_routed"])
    dead = next(r for r in replicas if r.replica_id == victim["replica"])
    dead.kill()
    assert _frame(router.execute(plan)).equals(
        _frame(router.execute(plan)))
    # resurrect at the SAME address; backoff probing must bring it back
    revived = ReplicaServer(dead.replica_id, host=dead.host,
                            port=dead.port).start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if router.health()["replicas_down"] == 0:
                break
            time.sleep(0.05)
        assert router.health()["replicas_down"] == 0
        # the down/up transitions are trace instants (fleet_replica_*)
        names = [s["name"] for s in tracing.spans()]
        assert "fleet_replica_down" in names
        assert "fleet_replica_up" in names
    finally:
        revived.kill()


def test_replica_hang_is_downed_by_liveness_deadline(fleet, tmp_path):
    """The `replica-hang` site: socket open, pings unanswered — only
    the liveness deadline can classify it, and queries route around."""
    router, replicas = fleet
    with faults.scoped(("replica-hang", dict(at=(1,)))):
        # Under load a HEALTHY replica can transiently miss pings and
        # flap down before probing revives it; only the wedged replica
        # stays down.  Wait for the down set to settle to exactly it.
        deadline = time.monotonic() + 10.0
        down = []
        while time.monotonic() < deadline:
            down = [r["replica"] for r in router.health()["replicas"]
                    if r["state"] == "down"]
            hung_ids = [r.replica_id for r in replicas if r._hung]
            if hung_ids and down == hung_ids:
                break
            time.sleep(0.05)
    hung = next(r.replica_id for r in replicas if r._hung)
    assert down == [hung]
    plan = _two_stage_plan(tmp_path, n=2000, tag="-h")
    assert _frame(router.execute(plan)) is not None
    assert all(r["queries_routed"] == 0 for r in
               router.health()["replicas"] if r["replica"] == hung)


def test_drained_replica_sheds_to_siblings(fleet, tmp_path):
    router, replicas = fleet
    plan = _two_stage_plan(tmp_path, n=2000, tag="-d")
    router.execute(plan)
    affine = next(r for r in router.health()["replicas"]
                  if r["queries_routed"])
    next(r for r in replicas
         if r.replica_id == affine["replica"]).drain(timeout_s=2.0)
    got = router.execute(plan)  # rerouted, not lost
    assert got.num_rows > 0


def test_all_replicas_dead_is_query_lost(tmp_path):
    config.conf.set(config.FLEET_RETRIES.key, 1)
    config.conf.set(config.FLEET_PROBE_BACKOFF_MS.key, 10)
    r = ReplicaServer("solo").start()
    router = FleetRouter([(r.replica_id, r.addr)], heartbeat=False)
    try:
        r.kill()
        before = xla_stats.fleet_stats()["fleet_queries_lost"]
        with pytest.raises(FleetQueryLost):
            router.execute(_two_stage_plan(tmp_path, n=500, tag="-l"))
        assert xla_stats.fleet_stats()["fleet_queries_lost"] == before + 1
    finally:
        router.close()


def test_plan_error_is_fatal_not_rerouted(fleet):
    """A broken plan fails the same way on every replica — the router
    must surface it once, not burn retries across the fleet."""
    router, _ = fleet
    with pytest.raises(FleetQueryFailed):
        router.execute({"kind": "no_such_operator"})
    assert router.health()["replicas_down"] == 0


class _SlowService:
    """QueryService stand-in that straggles for a fixed wall."""

    def __init__(self, inner, delay_s):
        self._inner = inner
        self._delay_s = delay_s

    def submit(self, plan, **kw):
        time.sleep(self._delay_s)
        return self._inner.submit(plan, **kw)

    def shutdown(self, **kw):
        self._inner.shutdown(**kw)


def test_hedge_races_straggler_across_replicas(fleet, tmp_path):
    """Cross-replica speculation: the affine replica straggles past
    multiplier x median, a hedge races from the next rendezvous
    position and wins — first-wins commit makes the duplicate safe."""
    from blaze_tpu.serving import QueryService
    config.conf.set(config.FLEET_HEDGE_ENABLE.key, "true")
    router, replicas = fleet
    router._hedge = True
    router._hedge_mult = 2.0
    router._hedge_min_s = 0.05
    plan = _two_stage_plan(tmp_path, n=2000, tag="-g")
    base = _frame(router.execute(plan))  # warm + seeds the median wall
    # wedge the affine replica's service so its next query straggles
    affine = router._rank(router.fingerprint(plan))[0].replica_id
    victim = next(r for r in replicas if r.replica_id == affine)
    victim._service = _SlowService(victim.service(), delay_s=2.0)
    before = xla_stats.fleet_stats()
    got = _frame(router.execute(plan))
    assert got.equals(base)
    after = xla_stats.fleet_stats()
    assert after["fleet_hedges"] == before["fleet_hedges"] + 1
    assert after["fleet_hedge_wins"] == before["fleet_hedge_wins"] + 1
    assert router.health()["replicas_down"] == 0  # slow is not dead


# -- health surfaces ---------------------------------------------------------

def test_fleet_health_module_surface(fleet, tmp_path):
    router, _ = fleet
    router.execute(_two_stage_plan(tmp_path, n=500, tag="-s"))
    payload = fleet_health()
    assert any(h["queries_routed"] >= 1 for h in payload["routers"])
    assert payload["counters"]["fleet_queries_completed"] >= 1
    json.dumps(payload, default=str)  # must be JSON-serializable


def test_fleet_http_endpoint(fleet, tmp_path):
    from blaze_tpu.bridge import profiling
    router, _ = fleet
    router.execute(_two_stage_plan(tmp_path, n=500, tag="-e"))
    port = profiling.start_http_service(0)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleet", timeout=10) as resp:
        assert resp.status == 200
        body = json.loads(resp.read())
    assert "routers" in body and "counters" in body
    assert any(r["queries_routed"] >= 1 for r in body["routers"])


def test_history_rollup_attributes_queries_to_replicas(tmp_path):
    d = str(tmp_path / "hist")
    config.conf.set(config.HISTORY_ENABLE.key, "true")
    config.conf.set(config.HISTORY_DIR.key, d)
    history.reset_conf_probe()
    try:
        for qid, replica in (("q-a", "r0"), ("q-b", "r0"),
                             ("q-c", "r1")):
            config.conf.set(config.FLEET_REPLICA_ID.key, replica)
            history.note_admitted(qid, tenant="t", deadline_ms=0,
                                  mem_quota=0)
            history.note_finished(qid, status="done", tenant="t",
                                  wall_s=0.1)
        store = history.HistoryStore(d)
        assert store.summary("q-a")["replica"] == "r0"
        roll = store.rollup()
        by_replica = {k: v["queries"] for k, v in
                      roll["replicas"].items()}
        assert by_replica == {"r0": 2, "r1": 1}
        # the soak's invariant: per-replica counts sum to the total
        assert sum(by_replica.values()) == roll["queries"]
    finally:
        for opt in (config.HISTORY_ENABLE, config.HISTORY_DIR):
            config.conf.unset(opt.key)
        history.reset_conf_probe()


# -- process-mode replica ----------------------------------------------------

@pytest.mark.slow
def test_spawned_replica_process_drains_on_sigterm():
    import signal

    from blaze_tpu.fleet import spawn_replica, wire
    proc, addr = spawn_replica("proc-r0")
    try:
        hello = wire.request(addr, {"kind": "hello"}, timeout_s=10.0)
        assert hello["replica_id"] == "proc-r0"
        assert hello["pid"] == proc.pid
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
