"""ColumnBatch: Arrow ⇄ device round trips, selection masks, compaction."""

import numpy as np
import pyarrow as pa
import jax.numpy as jnp

from blaze_tpu import schema as S
from blaze_tpu.batch import ColumnBatch, DeviceColumn, HostColumn, round_capacity


def _sample_rb():
    return pa.record_batch({
        "i": pa.array([1, 2, None, 4, 5], type=pa.int64()),
        "f": pa.array([1.5, None, 3.5, 4.5, 5.5], type=pa.float64()),
        "s": pa.array(["a", "bb", None, "dddd", "e"]),
        "b": pa.array([True, False, True, None, False]),
        "d": pa.array([0, 1, 2, 3, None], type=pa.date32()),
    })


def test_round_capacity():
    assert round_capacity(0) == 128
    assert round_capacity(1) == 128
    assert round_capacity(128) == 128
    assert round_capacity(129) == 256


def test_arrow_roundtrip():
    rb = _sample_rb()
    cb = ColumnBatch.from_arrow(rb)
    assert cb.num_rows == 5
    # host-resident batches are unpadded (numpy needs no static shapes);
    # device-resident ones pad to the 128-lane tile
    from blaze_tpu.bridge.placement import host_resident
    assert cb.capacity == (5 if host_resident() else 128)
    assert isinstance(cb.columns[0], DeviceColumn)
    assert isinstance(cb.columns[2], HostColumn)
    back = cb.to_arrow()
    assert back.equals(rb)


def test_validity_and_padding():
    cb = ColumnBatch.from_arrow(_sample_rb())
    col = cb.columns[0]
    v = np.asarray(col.validity)
    assert v[:5].tolist() == [True, True, False, True, True]
    assert not v[5:].any()


def test_selection_and_compact():
    cb = ColumnBatch.from_arrow(_sample_rb())
    sel = jnp.asarray(np.arange(cb.capacity) % 2 == 0)  # keep rows 0, 2, 4
    out = cb.with_selection(sel)
    assert out.selected_count() == 3
    packed = out.compact()
    assert packed.num_rows == 3
    rb = packed.to_arrow()
    assert rb.column(0).to_pylist() == [1, None, 5]
    assert rb.column(2).to_pylist() == ["a", None, "e"]


def test_selection_chaining():
    cb = ColumnBatch.from_arrow(_sample_rb())
    s1 = jnp.asarray(np.arange(cb.capacity) < 4)
    s2 = jnp.asarray(np.arange(cb.capacity) >= 2)
    out = cb.with_selection(s1).with_selection(s2)
    assert out.selected_count() == 2
    assert out.compact().to_arrow().column(0).to_pylist() == [None, 4]


def test_concat():
    cb1 = ColumnBatch.from_arrow(_sample_rb())
    cb2 = ColumnBatch.from_arrow(_sample_rb())
    out = ColumnBatch.concat([cb1, cb2])
    assert out.num_rows == 10
    assert out.to_arrow().column(0).to_pylist() == [1, 2, None, 4, 5] * 2


def test_decimal_roundtrip():
    import decimal as pydec
    rb = pa.record_batch({
        "dec": pa.array([None, pydec.Decimal("1.00"), pydec.Decimal("250.00")],
                        type=pa.decimal128(10, 2)),
    })
    cb = ColumnBatch.from_arrow(rb)
    col = cb.columns[0]
    assert isinstance(col, DeviceColumn)
    # unscaled representation: 1 -> 100, 250 -> 25000
    assert np.asarray(col.data)[:3].tolist() == [0, 100, 25000]
    back = cb.to_arrow()
    assert back.column(0).to_pylist()[1:] == [__import__("decimal").Decimal("1.00"),
                                              __import__("decimal").Decimal("250.00")]


def test_timestamp_roundtrip():
    rb = pa.record_batch({
        "ts": pa.array([1_000_000, None, 3_000_000], type=pa.timestamp("us")),
    })
    cb = ColumnBatch.from_arrow(rb)
    assert cb.to_arrow().equals(rb)


def test_select_columns():
    cb = ColumnBatch.from_arrow(_sample_rb())
    out = cb.select_columns([2, 0])
    assert out.schema.names == ["s", "i"]
    assert out.to_arrow().column(1).to_pylist() == [1, 2, None, 4, 5]
