"""HTTP surface conformance: every route the profiling service
declares in `profiling.ROUTES` answers with its documented status, a
correct Content-Type, and a parseable body — including the new
/stats, /progress and /query/<qid>/bottleneck endpoints — plus the
`tools.top` CLI against a live server and the tools/ci_check.sh gate.
"""

import json
import os
import subprocess
import time
import urllib.error
import urllib.request

import pytest

from blaze_tpu import config
from blaze_tpu.bridge import history, profiling, tracing, ui
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import statstore
from blaze_tpu.serving import progress

_QID = "q-conf"
_FP = "fp-conf"

#: per-route request query string (avoids side effects: /trace/start
#: with a bogus param is rejected before any profiler state changes)
_QUERY = {"/trace/start": "?nope=1", "/serving/cancel": f"?qid={_QID}"}

#: allowed statuses; everything not listed must 200 once seeded
_EXPECT = {"/trace/start": {400},
           "/trace/stop": {200, 500}}  # 500: no active profiler trace

_CTYPE = {"/metrics.prom": "text/plain", "/auron.html": "text/html"}


@pytest.fixture(autouse=True)
def seeded_service(tmp_path):
    """A live service with every data plane populated for _QID."""
    MemManager.init(4 << 30)
    ui.reset()
    progress.reset()
    config.conf.set(config.TRACE_ENABLE.key, "on")
    config.conf.set(config.HISTORY_ENABLE.key, "true")
    config.conf.set(config.HISTORY_DIR.key, str(tmp_path / "hist"))
    config.conf.set(config.STATS_ENABLE.key, "on")
    config.conf.set(config.STATS_DIR.key, str(tmp_path / "stats"))
    for mod in (tracing, history, statstore):
        mod.reset_conf_probe()

    with tracing.execution_context(query=_QID):
        with tracing.span("task", stage=0):
            time.sleep(0.002)
    profiling.record_metrics({"name": "ConfSeedExec",
                              "values": {"output_rows": 1},
                              "children": []})
    profiling.record_profile(_QID, {"query_id": _QID, "wall_ns": 1000,
                                    "tree": None, "output_rows": 1})
    history.note_admitted(_QID, tenant="t")
    history.note_finished(_QID, status="done", tenant="t", wall_s=0.01)
    statstore.ingest({"fingerprint": _FP, "wall_s": 0.01,
                      "task_ns": [1_000_000], "counters": {},
                      "fallback_reasons": {}, "stages": []})
    progress.note_query_start(_QID, fingerprint=_FP)
    progress.note_stage_start(_QID, 0, 2)
    progress.note_task_done(_QID, 0)

    port = profiling.start_http_service()
    try:
        yield port
    finally:
        profiling.stop_http_service()
        for opt in (config.TRACE_ENABLE, config.HISTORY_ENABLE,
                    config.HISTORY_DIR, config.STATS_ENABLE,
                    config.STATS_DIR):
            config.conf.unset(opt.key)
        for mod in (tracing, history, statstore):
            mod.reset_conf_probe()
        tracing.stop_tracing()
        progress.reset()
        ui.reset()


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.headers.get("Content-Type"), \
                r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read().decode()


def _concrete(route):
    return (route.replace("<qid>", _QID).replace("<fingerprint>", _FP)
            + _QUERY.get(route, ""))


@pytest.mark.parametrize("route", profiling.ROUTES)
def test_route_conformance(seeded_service, route):
    code, ctype, body = _get(seeded_service, _concrete(route))
    assert code in _EXPECT.get(route, {200}), \
        f"{route}: status {code}, body {body[:200]}"
    want_ctype = _CTYPE.get(route, "application/json")
    assert ctype and ctype.startswith(want_ctype), \
        f"{route}: Content-Type {ctype!r}"
    if want_ctype == "application/json":
        json.loads(body)  # every JSON route parses, error bodies too


def test_unknown_path_404_lists_all_routes(seeded_service):
    code, _ctype, body = _get(seeded_service, "/definitely/not/a/route")
    assert code == 404
    assert json.loads(body)["paths"] == list(profiling.ROUTES)


def test_bottleneck_endpoint_payload(seeded_service):
    code, _ctype, body = _get(seeded_service, f"/query/{_QID}/bottleneck")
    assert code == 200
    rep = json.loads(body)
    assert rep["v"] == 1
    assert rep["dominant"] in rep["categories"]
    assert rep["categories"]["host_compute"] >= 0.002  # the task span
    assert sum(rep["categories"].values()) == pytest.approx(
        rep["wall_s"], rel=0.01)


def test_stats_endpoints_round_trip(seeded_service):
    code, _c, body = _get(seeded_service, "/stats")
    assert code == 200
    assert any(s["fingerprint"] == _FP for s in json.loads(body))
    code, _c, body = _get(seeded_service, f"/stats/{_FP}")
    assert code == 200
    assert json.loads(body)["run_count"] == 1
    code, _c, body = _get(seeded_service, "/stats/nope")
    assert code == 404
    assert _FP in json.loads(body)["known"]


def test_progress_endpoints_round_trip(seeded_service):
    code, _c, body = _get(seeded_service, f"/query/{_QID}/progress")
    assert code == 200
    p = json.loads(body)
    assert p["tasks_done"] == 1 and p["tasks_total"] == 2
    code, _c, body = _get(seeded_service, "/progress")
    assert code == 200
    assert [q["query_id"] for q in json.loads(body)["running"]] == [_QID]


def test_top_cli_once_against_live_server(seeded_service):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [os.sys.executable, "-m", "blaze_tpu.tools.top", "--port",
         str(seeded_service), "--once"],
        capture_output=True, text=True, timeout=60, cwd=repo,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    assert "QUERY" in out.stdout and _QID in out.stdout
    out = subprocess.run(
        [os.sys.executable, "-m", "blaze_tpu.tools.top", "--port",
         str(seeded_service), "--once", "--json"],
        capture_output=True, text=True, timeout=60, cwd=repo,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    assert any(q["query_id"] == _QID
               for q in json.loads(out.stdout)["running"])


def test_top_cli_errors_cleanly_without_server():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [os.sys.executable, "-m", "blaze_tpu.tools.top", "--port", "1",
         "--once"],
        capture_output=True, text=True, timeout=60, cwd=repo,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 1
    assert "no response" in out.stderr


def test_ci_check_script_is_wired():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "tools", "ci_check.sh")
    assert os.path.exists(script)
    assert os.access(script, os.X_OK), "tools/ci_check.sh not executable"
    subprocess.run(["bash", "-n", script], check=True)
    with open(script) as f:
        text = f.read()
    assert "blaze_tpu.tools.sentinel" in text and "--ci" in text
    assert "pytest" in text
