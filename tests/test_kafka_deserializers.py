"""ops/kafka.py deserializer contract: malformed JSON, null records,
missing fields -> null, under both the direct JSON path and the framed
mock-scan path, plus a seeded property test that mock-scan framing
round-trips record boundaries at every batch size."""

import json
import random

import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.memory import MemManager
from blaze_tpu.ops.kafka import (JsonDeserializer, KafkaRecord,
                                 MockKafkaScanExec, schema_with_event_time)
from blaze_tpu.schema import FLOAT64, INT64, UTF8, Field, Schema

pytestmark = pytest.mark.stream


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


SCHEMA = Schema([Field("id", INT64, True), Field("name", UTF8, True),
                 Field("score", FLOAT64, True)])


def _collect(plan):
    return pa.Table.from_batches([b.compact().to_arrow()
                                  for b in plan.execute(0)])


# -- direct JSON path ---------------------------------------------------

def test_json_malformed_record_is_all_null():
    rb = JsonDeserializer(SCHEMA).deserialize([b"{not json at all"])
    assert rb.num_rows == 1
    assert all(rb.column(i)[0].as_py() is None for i in range(3))


def test_json_null_record_is_all_null():
    rb = JsonDeserializer(SCHEMA).deserialize(
        [None, b'{"id": 1, "name": "a", "score": 0.5}'])
    assert rb.column(0).to_pylist() == [None, 1]
    assert rb.column(1).to_pylist() == [None, "a"]
    assert rb.column(2).to_pylist() == [None, 0.5]


def test_json_missing_field_is_null():
    rb = JsonDeserializer(SCHEMA).deserialize(
        [b'{"id": 7}', b'{"name": "b", "score": 2.0}'])
    assert rb.column(0).to_pylist() == [7, None]
    assert rb.column(1).to_pylist() == [None, "b"]
    assert rb.column(2).to_pylist() == [None, 2.0]


def test_json_type_coercion_and_invalid_values():
    rb = JsonDeserializer(SCHEMA).deserialize([
        b'{"id": "42", "name": 3, "score": "1.5"}',   # coercible strings
        b'{"id": "xyz", "name": {"a": 1}, "score": "n/a"}',
        b'[1, 2, 3]'])                                # non-object JSON
    assert rb.column(0).to_pylist() == [42, None, None]
    # non-string scalars/objects render as JSON text for utf8 columns
    assert rb.column(1).to_pylist() == ["3", '{"a": 1}', None]
    assert rb.column(2).to_pylist() == [1.5, None, None]


# -- framed mock-scan path ----------------------------------------------

def _recs(values):
    return [KafkaRecord(value=v, offset=i, timestamp_ms=100 * i)
            for i, v in enumerate(values)]


def test_mock_scan_framed_null_and_malformed():
    recs = _recs([b'{"id": 1, "name": "a", "score": 0.1}',
                  None,
                  b"\xff\xfe garbage",
                  b'{"id": 4}'])
    scan = MockKafkaScanExec(SCHEMA, JsonDeserializer(SCHEMA), [recs])
    t = _collect(scan)
    assert t.num_rows == 4  # every record produces exactly one row
    assert t.column("id").to_pylist() == [1, None, None, 4]
    assert t.column("name").to_pylist() == ["a", None, None, None]


def test_mock_scan_event_time_column_rides_framing():
    recs = _recs([b'{"id": 1}', None, b'{"id": 3}'])
    scan = MockKafkaScanExec(SCHEMA, JsonDeserializer(SCHEMA), [recs],
                             event_time_field="__event_time")
    t = _collect(scan)
    # null/malformed records still carry their record timestamp
    assert t.column("__event_time").to_pylist() == [0, 100, 200]


def test_event_time_field_collision_rejected():
    with pytest.raises(ValueError, match="collides"):
        schema_with_event_time(SCHEMA, "id")


def test_mock_scan_framing_round_trips_record_boundaries():
    """Property test: for random record streams (valid/malformed/null
    mixed) and random batch sizes, the framed scan emits exactly one row
    per record, in offset order, with values surviving the frame/deframe
    round trip."""
    rng = random.Random(0xC0FFEE)
    for trial in range(12):
        n = rng.randint(1, 97)
        ids, payloads = [], []
        for i in range(n):
            shape = rng.random()
            if shape < 0.1:
                ids.append(None)
                payloads.append(None)           # tombstone record
            elif shape < 0.2:
                ids.append(None)
                payloads.append(b"}malformed{")  # undecodable bytes
            else:
                ids.append(i)
                payloads.append(json.dumps(
                    {"id": i, "name": f"n{i}",
                     "score": i / 2}).encode("utf-8"))
        bs = rng.choice([1, 2, 3, 7, 16, 100])
        with config.scoped(**{config.BATCH_SIZE.key: bs}):
            scan = MockKafkaScanExec(SCHEMA, JsonDeserializer(SCHEMA),
                                     [_recs(payloads)],
                                     event_time_field="__ts")
            t = _collect(scan)
        assert t.num_rows == n, f"trial {trial}: lost/dup rows at bs={bs}"
        assert t.column("id").to_pylist() == ids
        # record boundaries preserved: timestamps stay in offset order
        assert t.column("__ts").to_pylist() == [100 * i for i in range(n)]
