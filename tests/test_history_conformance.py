"""History-surface conformance: `xla_stats.counter_families()` is the
single source of truth for the runtime counter plane, and both export
surfaces — the Prometheus exposition (`profiling.prometheus_text()`)
and the history rollup (`HistoryStore.rollup()['counters']`) — must
represent every family it declares.  A counter added to xla_stats
cannot silently ship on one surface but not the other, and every
history event type must stay documented.  Mirrors
tests/test_span_names.py / tests/test_fault_sites.py."""

import os

from blaze_tpu.bridge import history, profiling, xla_stats
from blaze_tpu.memory import MemManager

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


def _flat_counter_keys():
    keys = {}
    for fam, counters in xla_stats.counter_families().items():
        for k in counters:
            keys[k] = fam
    return keys


def test_counter_families_cover_the_known_planes():
    fams = set(xla_stats.counter_families())
    # the sweep below is vacuous if families stop registering
    assert len(fams) >= 12, sorted(fams)
    for expected in ("transfers", "pipeline", "exprs", "faults",
                     "shuffle", "stage_loop", "stream", "workers",
                     "speculation", "obs"):
        assert expected in fams


def test_every_counter_family_renders_in_prometheus_text():
    MemManager.init(4 << 30)
    text = profiling.prometheus_text()
    missing = []
    for k in _flat_counter_keys():
        want = (f"blaze_{k[:-len('_last')]}" if k.endswith("_last")
                else f"blaze_{k}_total")
        if want not in text:
            missing.append((k, want))
    assert not missing, f"counters absent from /metrics.prom: {missing}"


def test_every_counter_key_is_in_the_rollup_schema(tmp_path):
    rollup_keys = set(history.rollup_counter_keys())
    for k, fam in _flat_counter_keys().items():
        if k.endswith("_last"):
            assert k not in rollup_keys, (
                f"{k} is a point-in-time gauge; summing it across "
                f"queries is meaningless")
        else:
            assert k in rollup_keys, f"{fam}.{k} missing from rollup"
    # and an actual (empty) rollup pre-seeds every key at zero
    r = history.HistoryStore(str(tmp_path)).rollup()
    assert set(r["counters"]) == rollup_keys
    assert all(v == 0 for v in r["counters"].values())


def test_rollup_and_prometheus_agree_on_the_counter_plane():
    """The two export surfaces are the same set: every summable counter
    the scrape exposes is aggregable from history, and vice versa."""
    MemManager.init(4 << 30)
    text = profiling.prometheus_text()
    for k in history.rollup_counter_keys():
        assert f"blaze_{k}_total" in text, (
            f"rollup key {k} has no Prometheus family")


def test_event_types_are_documented():
    with open(os.path.join(_REPO, "docs", "observability.md")) as f:
        docs = f.read()
    for event in sorted(history.EVENT_TYPES):
        assert f"`{event}`" in docs, (
            f"history event type {event!r} missing from "
            f"docs/observability.md")


def test_history_knobs_are_documented():
    from blaze_tpu import config
    with open(os.path.join(_REPO, "docs", "configuration.md")) as f:
        docs = f.read()
    for opt in (config.HISTORY_ENABLE, config.HISTORY_DIR,
                config.HISTORY_MAX_EVENTS, config.HISTORY_MAX_QUERIES,
                config.SENTINEL_THRESHOLD):
        assert opt.key in docs, opt.key
