"""Adaptive partial-aggregate skipping (ref AGG_TRIGGER_PARTIAL_SKIPPING,
agg_table.rs:108-122): the ratio probe, the pass-through lane, the
memory-pressure mode switch, and bit-exactness of the final merge."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu import schema as S
from blaze_tpu.bridge import xla_stats
from blaze_tpu.exprs import col
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.agg import AggExec, AggMode, make_agg


@pytest.fixture(autouse=True)
def big_budget():
    MemManager.init(4 << 30)
    yield
    MemManager.init(4 << 30)


def partial_agg(table, group_cols, aggs, batch_rows=512, **conf):
    scan = MemoryScanExec.from_arrow(table, batch_rows=batch_rows)
    schema = S.Schema.from_arrow(table.schema)
    group_exprs = [(col(schema.index_of(c), c), c) for c in group_cols]
    agg_list = []
    for fname, in_col, out_name in aggs:
        children = [col(schema.index_of(in_col), in_col)] if in_col else []
        agg_list.append((make_agg(fname, children), AggMode.PARTIAL,
                         out_name))
    plan = AggExec(scan, group_exprs, agg_list)
    with config.scoped(**conf):
        return plan.execute_collect().to_arrow(), plan


def finalize(partial_tbl, num_group_cols, specs):
    """Final-stage merge over a partial-form table: specs are
    (fname, nacc) per agg in order, acc columns positional."""
    scan = MemoryScanExec.from_arrow(partial_tbl)
    names = partial_tbl.schema.names
    groups = [(col(i, names[i]), names[i]) for i in range(num_group_cols)]
    aggs, pos = [], num_group_cols
    for fname, nacc in specs:
        mode = AggMode.FINAL if fname == "avg" else AggMode.PARTIAL_MERGE
        aggs.append((make_agg(fname, [col(pos + t) for t in range(nacc)]),
                     mode, fname))
        pos += nacc
    plan = AggExec(scan, groups, aggs)
    return plan.execute_collect().to_arrow()


def sort_table(t):
    keys = [(n, "ascending") for n in t.schema.names]
    return t.take(pa.compute.sort_indices(t, sort_keys=keys))


HIGH_NDV_CONF = {"auron.tpu.partialAgg.skipping.minRows": 1000,
                 "auron.tpu.partialAgg.skipping.ratio": 0.5}


def _high_ndv_table(n=6000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "k": pa.array(rng.integers(0, n * 8, n)),
        "v": pa.array(rng.integers(-50, 50, n)),
    })


def test_ratio_probe_triggers_switch():
    t = _high_ndv_table()
    got, plan = partial_agg(t, ["k"], [("count", "v", "c")],
                            **HIGH_NDV_CONF)
    assert plan.metrics.get("partial_skipped") == 1
    assert plan.metrics.get("passthrough_rows") > 0
    # every input row is represented exactly once across the mixed
    # hashed-prefix + pass-through-tail output
    assert sum(got.column("c.count").to_pylist()) == t.num_rows


def test_low_cardinality_never_switches():
    n = 6000
    t = pa.table({"k": pa.array(np.arange(n) % 5),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    got, plan = partial_agg(t, ["k"], [("count", "v", "c")],
                            **HIGH_NDV_CONF)
    assert plan.metrics.get("partial_skipped") == 0
    assert got.num_rows == 5


def test_min_rows_gates_the_probe():
    # high-NDV input that ENDS before the probe window does: no switch
    t = _high_ndv_table(n=800)
    got, plan = partial_agg(
        t, ["k"], [("count", "v", "c")],
        **{"auron.tpu.partialAgg.skipping.minRows": 100000,
           "auron.tpu.partialAgg.skipping.ratio": 0.0})
    assert plan.metrics.get("partial_skipped") == 0


def test_enable_off_never_switches():
    t = _high_ndv_table()
    got, plan = partial_agg(
        t, ["k"], [("count", "v", "c")],
        **dict(HIGH_NDV_CONF,
               **{"auron.tpu.partialAgg.skipping.enable": False}))
    assert plan.metrics.get("partial_skipped") == 0


def test_final_results_bit_identical_across_modes():
    """sum/count/avg/min/max over INTEGER values: the skipped partial
    stream must merge to the byte-identical final table."""
    rng = np.random.default_rng(3)
    n = 8000
    t = pa.table({
        "k": pa.array(rng.integers(0, n * 4, n)),
        "ks": pa.array([f"g{int(x):05d}" for x in rng.integers(0, n * 4, n)]),
        "v": pa.array(np.where(rng.random(n) < 0.1, None,
                               rng.integers(-100, 100, n)).tolist(),
                      type=pa.int64()),
    })
    aggs = [("sum", "v", "s"), ("count", "v", "c"), ("avg", "v", "a"),
            ("min", "v", "mn"), ("max", "v", "mx")]
    specs = [("sum", 1), ("count", 1), ("avg", 2), ("min", 1), ("max", 1)]
    p_on, plan_on = partial_agg(
        t, ["k", "ks"], aggs,
        **{"auron.tpu.partialAgg.skipping.minRows": 500,
           "auron.tpu.partialAgg.skipping.ratio": 0.5})
    p_off, plan_off = partial_agg(
        t, ["k", "ks"], aggs,
        **{"auron.tpu.partialAgg.skipping.enable": False})
    assert plan_on.metrics.get("partial_skipped") == 1
    assert plan_off.metrics.get("partial_skipped") == 0
    assert p_on.schema == p_off.schema  # same partial wire schema
    f_on = sort_table(finalize(p_on, 2, specs))
    f_off = sort_table(finalize(p_off, 2, specs))
    assert f_on.equals(f_off)


def test_distinct_style_two_level_rollup_identical():
    """count-distinct rollup shape: inner partial group-by (k, v) with
    skipping forced, outer count over the merged inner — identical to
    the unskipped rollup."""
    rng = np.random.default_rng(5)
    n = 5000
    t = pa.table({"k": pa.array(rng.integers(0, 40, n)),
                  "v": pa.array(rng.integers(0, n, n))})

    def rollup(skip):
        conf = ({"auron.tpu.partialAgg.skipping.minRows": 200,
                 "auron.tpu.partialAgg.skipping.ratio": 0.1} if skip
                else {"auron.tpu.partialAgg.skipping.enable": False})
        inner, plan = partial_agg(t, ["k", "v"], [("count", "v", "c")],
                                  **conf)
        assert bool(plan.metrics.get("partial_skipped")) is skip
        # merge the (possibly repeated) inner keys, then count distinct
        # v per k = rows per k of the merged inner table
        merged = finalize(inner, 2, [("count", 1)])
        df = merged.to_pandas().groupby("k").size().sort_index()
        return df

    pd.testing.assert_series_equal(rollup(True), rollup(False))


def test_memory_pressure_prefers_passthrough_over_spill():
    rng = np.random.default_rng(2)
    n = 50000
    t = pa.table({"k": pa.array(rng.integers(0, 5000, n)),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    MemManager.init(150_000)
    mm = MemManager.get()
    got, plan = partial_agg(
        t, ["k"], [("count", "v", "c")], batch_rows=4096,
        **{"auron.tpu.partialAgg.skipping.onSpill": True,
           "auron.tpu.partialAgg.skipping.ratio": 1.1})
    assert plan.metrics.get("spill_count") == 0
    assert plan.metrics.get("partial_skipped") == 1
    assert mm.total_pressure_releases >= 1
    totals = {}
    for k, c in zip(got.column("k").to_pylist(),
                    got.column("c.count").to_pylist()):
        totals[k] = totals.get(k, 0) + c
    want = t.to_pandas().groupby("k").v.count()
    assert totals == {k: int(v) for k, v in want.items()}


def test_on_spill_off_still_spills():
    rng = np.random.default_rng(2)
    n = 50000
    t = pa.table({"k": pa.array(rng.integers(0, 5000, n)),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    MemManager.init(150_000)
    got, plan = partial_agg(
        t, ["k"], [("count", "v", "c")], batch_rows=4096,
        **{"auron.tpu.partialAgg.skipping.ratio": 1.1})
    assert plan.metrics.get("spill_count") >= 1
    assert plan.metrics.get("partial_skipped") == 0


def test_skip_and_spill_interleave():
    """Spill (onSpill off) during the probe window, then the ratio
    probe still switches: spilled runs + flush + pass-through tail all
    merge to the right totals."""
    rng = np.random.default_rng(9)
    n = 40000
    t = pa.table({"k": pa.array(rng.integers(0, n * 8, n)),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    MemManager.init(400_000)
    got, plan = partial_agg(
        t, ["k"], [("count", "v", "c")], batch_rows=2048,
        **{"auron.tpu.partialAgg.skipping.minRows": 20000,
           "auron.tpu.partialAgg.skipping.ratio": 0.5})
    assert plan.metrics.get("partial_skipped") == 1
    assert sum(got.column("c.count").to_pylist()) == n


def test_xla_stats_counters_and_explain_footer():
    xla_stats.reset()
    t = _high_ndv_table()
    before = xla_stats.snapshot()
    _got, _plan = partial_agg(t, ["k"], [("count", "v", "c")],
                              **HIGH_NDV_CONF)
    d = xla_stats.delta(before)
    assert d["partial_agg_skip_events"] == 1
    assert d["partial_agg_skipped_rows"] > 0
    assert d["partial_agg_probe_rows"] >= 1000
    assert d["partial_agg_probe_groups"] > 0
    assert d["partial_agg_switch_rows"] > 0
    from blaze_tpu.bridge.metrics import MetricNode
    from blaze_tpu.plan.explain import QueryProfile
    prof = QueryProfile(query_id="t", wall_ns=1,
                        tree=MetricNode("root"), partitions=1,
                        exec_mode="local", xla=d)
    text = prof.render_text()
    assert "partial agg:" in text
    assert "probe_ratio=" in text and "skip_events=1" in text


def test_passthrough_respects_selection_mask():
    """A filtered batch entering the pass-through lane must only emit
    SELECTED rows (compaction, not capacity, defines the group count)."""
    n = 4000
    t = pa.table({"k": pa.array(np.arange(n)),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    from blaze_tpu.exprs import BinaryExpr, lit
    from blaze_tpu.ops import FilterExec
    scan = MemoryScanExec.from_arrow(t, batch_rows=512)
    filt = FilterExec(scan, [BinaryExpr("<", col(0, "k"), lit(n // 2))])
    plan = AggExec(filt, [(col(0, "k"), "k")],
                   [(make_agg("sum", [col(1, "v")]), AggMode.PARTIAL, "s")])
    with config.scoped(**{"auron.tpu.partialAgg.skipping.minRows": 256,
                          "auron.tpu.partialAgg.skipping.ratio": 0.5}):
        got = plan.execute_collect().to_arrow()
    assert plan.metrics.get("partial_skipped") == 1
    assert sum(got.column("s.sum").to_pylist()) == n // 2
    assert max(got.column("k").to_pylist()) == n // 2 - 1
