"""Column-pruning pass (plan/column_pruning.py — Catalyst ColumnPruning
analog): scans narrow to referenced columns, BoundReferences remap, and
results are identical with the pass on or off."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import AggExec, AggMode, FilterExec, ProjectExec
from blaze_tpu.ops.agg.functions import make_agg
from blaze_tpu.ops.joins import JoinType
from blaze_tpu.ops.joins.exec import BroadcastJoinExec
from blaze_tpu.ops.scan import ParquetScanExec
from blaze_tpu.plan.column_pruning import prune_columns
from blaze_tpu.schema import Schema


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _wide_file(tmp_path, n=5000, name="wide.parquet"):
    rng = np.random.default_rng(0)
    t = pa.table({f"c{i}": pa.array(rng.integers(0, 50, n))
                  for i in range(10)})
    p = str(tmp_path / name)
    pq.write_table(t, p)
    return p, t


def _collect(plan):
    out = []
    for p in range(plan.num_partitions):
        out.extend(b.compact().to_arrow() for b in plan.execute(p))
    return pa.Table.from_batches([b for b in out if b.num_rows])


def test_agg_over_filter_prunes_scan(tmp_path):
    p, t = _wide_file(tmp_path)
    def build():
        scan = ParquetScanExec(Schema.from_arrow(t.schema), [[p]])
        flt = FilterExec(scan, [BinaryExpr(">", col(3, "c3"), lit(10))])
        return AggExec(flt, [(col(7, "c7"), "k")],
                       [(make_agg("sum", [col(5)]), AggMode.COMPLETE,
                         "s")])
    pruned = prune_columns(build())
    # the scan under the pass reads only c3, c5, c7
    node = pruned
    while node.children:
        node = node.children[0]
    assert isinstance(node, ParquetScanExec)
    assert [f.name for f in node.schema] == ["c3", "c5", "c7"]
    got = _collect(pruned).to_pandas().sort_values("k").reset_index(
        drop=True)
    config.conf.set(config.COLUMN_PRUNING_ENABLE.key, False)
    try:
        want = _collect(build()).to_pandas().sort_values("k") \
            .reset_index(drop=True)
    finally:
        config.conf.unset(config.COLUMN_PRUNING_ENABLE.key)
    pd.testing.assert_frame_equal(got, want)


def test_join_prunes_both_sides(tmp_path):
    p1, t1 = _wide_file(tmp_path, name="l.parquet")
    p2, t2 = _wide_file(tmp_path, n=300, name="r.parquet")
    def build():
        l = ParquetScanExec(Schema.from_arrow(t1.schema), [[p1]])
        r = ParquetScanExec(Schema.from_arrow(t2.schema), [[p2]])
        j = BroadcastJoinExec(l, r, [col(2)], [col(4)], JoinType.INNER)
        # references l.c2, l.c6, r.c4 (=idx 14), r.c9 (=idx 19)
        return ProjectExec(j, [col(2), col(6), col(14), col(19)],
                           ["a", "b", "c", "d"])
    pruned = prune_columns(build())
    scans = []
    def walk(n):
        if isinstance(n, ParquetScanExec):
            scans.append([f.name for f in n.schema])
        for c in n.children:
            walk(c)
    walk(pruned)
    assert scans == [["c2", "c6"], ["c4", "c9"]]
    got = _collect(pruned).to_pandas().sort_values(
        ["a", "b", "c", "d"]).reset_index(drop=True)
    config.conf.set(config.COLUMN_PRUNING_ENABLE.key, False)
    try:
        want = _collect(build()).to_pandas().sort_values(
            ["a", "b", "c", "d"]).reset_index(drop=True)
    finally:
        config.conf.unset(config.COLUMN_PRUNING_ENABLE.key)
    pd.testing.assert_frame_equal(got, want)


def test_semi_join_is_a_barrier_but_descends(tmp_path):
    p1, t1 = _wide_file(tmp_path, name="l2.parquet")
    p2, t2 = _wide_file(tmp_path, n=300, name="r2.parquet")
    l = ParquetScanExec(Schema.from_arrow(t1.schema), [[p1]])
    r_scan = ParquetScanExec(Schema.from_arrow(t2.schema), [[p2]])
    r = AggExec(r_scan, [(col(4, "c4"), "k")],
                [(make_agg("count", [col(4)]), AggMode.COMPLETE, "n")])
    j = BroadcastJoinExec(l, r, [col(2)], [col(0)], JoinType.LEFT_SEMI)
    pruned = prune_columns(j)
    # left side untouched (semi barrier); right side pruned under agg
    assert len(pruned.children[0].schema) == 10
    inner = pruned.children[1].children[0]
    assert [f.name for f in inner.schema] == ["c4"]


def test_shared_broadcast_id_with_different_pruning(tmp_path):
    """Two plans sharing one broadcast_id but pruned to different build
    columns must not serve each other's cached join map (the cache key
    folds the build schema; reproduced wrong results before the fix)."""
    p1, t1 = _wide_file(tmp_path, name="probe.parquet")
    p2, t2 = _wide_file(tmp_path, n=300, name="build.parquet")

    def build(keep_idx, name):
        l = ParquetScanExec(Schema.from_arrow(t1.schema), [[p1]])
        r = ParquetScanExec(Schema.from_arrow(t2.schema), [[p2]])
        j = BroadcastJoinExec(l, r, [col(2)], [col(4)], JoinType.INNER,
                              broadcast_id="shared-bhj")
        return prune_columns(
            ProjectExec(j, [col(2), col(keep_idx)], ["k", name]))

    a = _collect(build(10 + 6, "v6")).to_pandas()   # right c6
    b = _collect(build(10 + 9, "v9")).to_pandas()   # right c9
    probe = t1.to_pandas()
    bld = t2.to_pandas()
    for out, cname, vname in ((a, "c6", "v6"), (b, "c9", "v9")):
        want = probe.merge(bld, left_on="c2", right_on="c4",
                           suffixes=("", "_r"))
        want_vals = sorted(want[cname + "_r"].tolist())
        assert sorted(out[vname].tolist()) == want_vals
