"""Converter completeness (VERDICT r2 #5): WindowExec / GenerateExec /
WindowGroupLimitExec conversion, SparkUDFWrapper-style expression
fallback, and the convert-strategy tagging + removeInefficientConverts
pass (ref NativeConverters.scala:399, AuronConvertStrategy.scala:205)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu.bridge.resource import put_resource, remove_resource
from blaze_tpu.convert import ConversionError, convert_spark_plan
from blaze_tpu.convert.strategy import (explain, remove_inefficient_converts,
                                        tag_plan)
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan

CAT = "org.apache.spark.sql.catalyst.expressions."
EXEC = "org.apache.spark.sql.execution."


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def attr(name, dt, eid):
    return [{"class": CAT + "AttributeReference", "num-children": 0,
             "name": name, "dataType": dt, "nullable": True,
             "exprId": {"id": eid, "jvmId": "u"}}]


def lit(value, dt):
    return [{"class": CAT + "Literal", "num-children": 0,
             "value": value, "dataType": dt}]


def sort_order(child, desc=False):
    return [{"class": CAT + "SortOrder", "num-children": 1,
             "direction": ("Descending" if desc else "Ascending"),
             "nullOrdering": ("NullsLast" if desc else "NullsFirst")}] + \
        child


def scan_node(attrs, files):
    return [{"class": EXEC + "FileSourceScanExec", "num-children": 0,
             "output": [a for a in attrs], "files": files}]


def plan_node(cls, fields, children):
    out = [{"class": EXEC + cls, "num-children": len(children), **fields}]
    for c in children:
        out += c
    return out


def window_expr(fn_nodes, name, eid):
    """Alias(WindowExpression(fn, WindowSpecDefinition()))"""
    spec = [{"class": CAT + "WindowSpecDefinition", "num-children": 0}]
    wex = [{"class": CAT + "WindowExpression", "num-children": 2}] + \
        fn_nodes + spec
    return [{"class": CAT + "Alias", "num-children": 1, "name": name,
             "exprId": {"id": eid, "jvmId": "u"}}] + wex


def _write(tmp_path, t, name="t.parquet"):
    p = str(tmp_path / name)
    pq.write_table(t, p)
    return [[p]]


def _run(ir):
    plan = create_plan(ir)
    out = []
    for p in range(plan.num_partitions):
        out.extend(b.compact().to_arrow() for b in plan.execute(p))
    out = [b for b in out if b.num_rows]
    return (pa.Table.from_batches(out).to_pandas() if out
            else pd.DataFrame())


# -- WindowExec -------------------------------------------------------------

def test_window_rank_and_agg(tmp_path):
    # pre-sorted by (g, v): Spark guarantees WindowExec input ordering by
    # inserting a SortExec below it
    t = pa.table({"g": pa.array([1, 1, 1, 2, 2], type=pa.int64()),
                  "v": pa.array([10.0, 20.0, 30.0, 5.0, 7.0])})
    files = _write(tmp_path, t)
    g, v = attr("g", "long", 1), attr("v", "double", 2)
    rn = window_expr([{"class": CAT + "RowNumber", "num-children": 0}],
                     "rn", 10)
    sm = window_expr(
        [{"class": CAT + "aggregate.AggregateExpression",
          "num-children": 1, "mode": "Complete",
          "resultId": {"id": 99, "jvmId": "u"}},
         {"class": CAT + "aggregate.Sum", "num-children": 1}] +
        attr("v", "double", 2), "running_sum", 11)
    plan = plan_node(
        "window.WindowExec",
        {"windowExpression": [rn, sm],
         "partitionSpec": [attr("g", "long", 1)],
         "orderSpec": [sort_order(attr("v", "double", 2))]},
        [scan_node([g[0], v[0]], files)])
    res = convert_spark_plan(plan)
    assert res.output_names == ["g", "v", "rn", "running_sum"]
    got = _run(res.plan)
    df = got.sort_values(["g", "v"]).reset_index(drop=True)
    assert df["rn"].tolist() == [1, 2, 3, 1, 2]
    np.testing.assert_allclose(df["running_sum"].tolist(),
                               [10.0, 30.0, 60.0, 5.0, 12.0])


def test_window_lead_lag(tmp_path):
    t = pa.table({"g": pa.array([1, 1, 1], type=pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0])})
    files = _write(tmp_path, t)
    g, v = attr("g", "long", 1), attr("v", "double", 2)
    ld = window_expr(
        [{"class": CAT + "Lead", "num-children": 3}] +
        attr("v", "double", 2) + lit("1", "integer") + lit(None, "double"),
        "nxt", 10)
    plan = plan_node(
        "window.WindowExec",
        {"windowExpression": [ld],
         "partitionSpec": [attr("g", "long", 1)],
         "orderSpec": [sort_order(attr("v", "double", 2))]},
        [scan_node([g[0], v[0]], files)])
    got = _run(convert_spark_plan(plan).plan)
    vals = got["nxt"].tolist()
    assert vals[:2] == [2.0, 3.0] and pd.isna(vals[2])


def test_window_group_limit(tmp_path):
    t = pa.table({"g": pa.array([1, 1, 1, 2, 2], type=pa.int64()),
                  "v": pa.array([10.0, 30.0, 20.0, 5.0, 7.0])})
    files = _write(tmp_path, t)
    g, v = attr("g", "long", 1), attr("v", "double", 2)
    plan = plan_node(
        "window.WindowGroupLimitExec",
        {"partitionSpec": [attr("g", "long", 1)],
         "orderSpec": [sort_order(attr("v", "double", 2))],
         "limit": 1,
         "rankLikeFunction": [{"class": CAT + "RowNumber",
                               "num-children": 0}]},
        [scan_node([g[0], v[0]], files)])
    res = convert_spark_plan(plan)
    assert res.output_names == ["g", "v"]  # filter only, no rank column
    got = _run(res.plan).sort_values("g").reset_index(drop=True)
    assert got["v"].tolist() == [10.0, 5.0]  # min v per group


# -- GenerateExec -----------------------------------------------------------

def test_generate_explode(tmp_path):
    t = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                  "xs": pa.array([[10, 20], [30]],
                                 type=pa.list_(pa.int64()))})
    files = _write(tmp_path, t)
    k = attr("k", "long", 1)
    xs = [{"class": CAT + "AttributeReference", "num-children": 0,
           "name": "xs",
           "dataType": {"type": "array", "elementType": "long",
                        "containsNull": True},
           "nullable": True, "exprId": {"id": 2, "jvmId": "u"}}]
    gen = [{"class": CAT + "Explode", "num-children": 1}] + xs
    plan = plan_node(
        "GenerateExec",
        {"generator": [gen], "outer": False,
         "requiredChildOutput": [k],
         "generatorOutput": [attr("x", "long", 3)]},
        [scan_node([k[0], xs[0]], files)])
    res = convert_spark_plan(plan)
    assert res.output_names == ["k", "x"]
    got = _run(res.plan)
    assert sorted(zip(got["k"], got["x"])) == [(1, 10), (1, 20), (2, 30)]


# -- expression fallback ----------------------------------------------------

def test_unsupported_expr_wraps_as_udf(tmp_path):
    t = pa.table({"x": pa.array([1, 2, 3], type=pa.int64())})
    files = _write(tmp_path, t)
    weird = [{"class": CAT + "ScalaUDF", "num-children": 1,
              "dataType": "long"}] + attr("x", "long", 1)
    plan = plan_node(
        "ProjectExec",
        {"projectList": [
            [{"class": CAT + "Alias", "num-children": 1, "name": "y",
              "exprId": {"id": 5, "jvmId": "u"}}] + weird]},
        [scan_node([attr("x", "long", 1)[0]], files)])
    res = convert_spark_plan(plan)  # converts: wrapped, not rejected
    wrapped = res.plan["exprs"][0]
    assert wrapped["kind"] == "udf"
    assert wrapped["name"].startswith("spark:ScalaUDF#")
    assert "serialized" in wrapped

    # host registers the evaluator (SparkAuronUDFWrapperContext analog)
    def times_ten(col):
        return pa.compute.multiply(col, 10)
    rid = f"udf://{wrapped['name']}"
    put_resource(rid, times_ten)
    try:
        got = _run(res.plan)
        assert got["y"].tolist() == [10, 20, 30]
    finally:
        remove_resource(rid)


def test_fallback_disabled_raises(tmp_path):
    t = pa.table({"x": pa.array([1], type=pa.int64())})
    files = _write(tmp_path, t)
    weird = [{"class": CAT + "ScalaUDF", "num-children": 1,
              "dataType": "long"}] + attr("x", "long", 1)
    plan = plan_node("ProjectExec", {"projectList": [weird]},
                     [scan_node([attr("x", "long", 1)[0]], files)])
    config.conf.set(config.UDF_FALLBACK_ENABLE.key, False)
    try:
        with pytest.raises(ConversionError, match="ScalaUDF"):
            convert_spark_plan(plan)
    finally:
        config.conf.unset(config.UDF_FALLBACK_ENABLE.key)


# -- strategy tagging -------------------------------------------------------

def test_tag_plan_reports_reasons(tmp_path):
    t = pa.table({"x": pa.array([1], type=pa.int64())})
    files = _write(tmp_path, t)
    plan = plan_node(
        "CollectLimitExec",  # unsupported top node
        {"limit": 5},
        [plan_node("FilterExec",
                   {"condition":
                    [{"class": CAT + "GreaterThan", "num-children": 2}] +
                    attr("x", "long", 1) + lit("0", "long")},
                   [scan_node([attr("x", "long", 1)[0]], files)])])
    tag = tag_plan(plan)
    assert not tag.convertible
    assert "CollectLimitExec" in tag.reason
    assert tag.children[0].convertible          # filter subtree converts
    assert tag.children[0].children[0].convertible  # scan converts
    report = explain(tag)
    assert "FALLBACK" in report and "native" in report


def test_remove_inefficient_converts_demotes_islands(tmp_path):
    t = pa.table({"x": pa.array([1], type=pa.int64())})
    files = _write(tmp_path, t)
    # an island in the middle: project(x) is convertible ON ITS OWN
    # MERITS (its unsupported child exposes output attrs, so tagging
    # substitutes a ConvertToNative-style placeholder), but its parent
    # and child are not native -> the island rule demotes it
    unsupported = [{"class": EXEC + "MysteryExec", "num-children": 1,
                    "output": [attr("x", "long", 1)]}] + \
        scan_node([attr("x", "long", 1)[0]], files)
    island = plan_node(
        "CollectLimitExec", {"limit": 1},
        [plan_node("ProjectExec",
                   {"projectList": [attr("x", "long", 1)]},
                   [unsupported])])
    tag2 = tag_plan(island)
    proj_tag = tag2.children[0]
    assert proj_tag.convertible          # per-node tagging via placeholder
    assert not proj_tag.children[0].convertible
    out = remove_inefficient_converts(tag2)
    assert not out.children[0].convertible
    assert "removeInefficientConverts" in out.children[0].reason

    # a node whose unsupported child has NO output attrs cannot be
    # tagged independently: the child's reason propagates
    blind = plan_node(
        "SortExec",
        {"sortOrder": [sort_order(attr("x", "long", 1))]},
        [plan_node("MysteryExec", {},
                   [scan_node([attr("x", "long", 1)[0]], files)])])
    tag3 = tag_plan(blind)
    assert not tag3.convertible
    assert "MysteryExec" in tag3.reason


def test_pyspark_shim_importable_and_gated():
    """The PySpark driver shim (convert/shim.py) is exercised only where
    pyspark exists; here we pin its import surface so refactors keep it
    loadable."""
    from blaze_tpu.convert import shim
    assert callable(shim.execute_dataframe)
    assert callable(shim.extract_plan_json)
    pytest.importorskip("pyspark")  # full path needs a JVM + Spark
