"""Operator fuzz tests vs pandas/pyarrow oracles (ref agg_exec.rs:803
fuzztest, sort_exec.rs fuzz).

Random schemas with nulls/strings/decimals through agg, sort, joins and
window; seeds are fixed per case so failures reproduce — print the seed on
assert to minimize by hand."""

import decimal as pydec

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

pytestmark = pytest.mark.slow  # deselect with -m 'not slow'

from blaze_tpu.exprs import col
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import (AggExec, AggMode, MemoryScanExec, SortExec,
                           make_agg)
from blaze_tpu.ops.joins import JoinType
from blaze_tpu.ops.joins.exec import (ShuffledHashJoinExec,
                                      SortMergeJoinExec)

SEEDS = [1, 7, 42, 1337]


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _rand_table(rng, n, with_strings=True, with_decimal=True,
                key_range=50):
    cols = {}
    key = rng.integers(0, key_range, n).astype(float)
    key[rng.random(n) < 0.06] = np.nan
    cols["k"] = pa.array([None if np.isnan(x) else int(x) for x in key],
                         type=pa.int64())
    v = rng.random(n) * 100
    vm = rng.random(n) < 0.08
    cols["v"] = pa.array(np.where(vm, None, v).tolist(), type=pa.float64())
    cols["i"] = pa.array(rng.integers(-1000, 1000, n), type=pa.int32())
    if with_strings:
        words = np.array(["", "a", "bb", "ccc", "Ddd", "éé",
                          "zz9"])
        s = words[rng.integers(0, len(words), n)]
        sm = rng.random(n) < 0.05
        cols["s"] = pa.array([None if m else x for x, m in zip(s, sm)],
                             type=pa.utf8())
    if with_decimal:
        d = rng.integers(-10**6, 10**6, n)
        dm = rng.random(n) < 0.05
        cols["d"] = pa.array(
            [None if m else pydec.Decimal(int(x)).scaleb(-2)
             for x, m in zip(d, dm)], type=pa.decimal128(12, 2))
    return pa.table(cols)


def _collect(plan):
    out = [b.compact().to_arrow() for b in plan.execute(0)]
    out = [b for b in out if b.num_rows]
    if not out:
        return pd.DataFrame()
    return pa.Table.from_batches(out).to_pandas()


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_agg_sum_count_min_max_avg(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(500, 6000))
    t = _rand_table(rng, n)
    plan = AggExec(
        MemoryScanExec.from_arrow(t, batch_rows=int(rng.integers(64, 1024))),
        [(col(0, "k"), "k"), (col(3, "s"), "s")],
        [(make_agg("sum", [col(1)]), AggMode.COMPLETE, "sum_v"),
         (make_agg("count", [col(1)]), AggMode.COMPLETE, "cnt_v"),
         (make_agg("min", [col(2)]), AggMode.COMPLETE, "min_i"),
         (make_agg("max", [col(2)]), AggMode.COMPLETE, "max_i"),
         (make_agg("avg", [col(1)]), AggMode.COMPLETE, "avg_v")])
    got = _collect(plan).sort_values(["k", "s"], na_position="first") \
        .reset_index(drop=True)
    df = t.to_pandas()
    want = df.groupby(["k", "s"], dropna=False, as_index=False).agg(
        sum_v=("v", lambda x: x.sum(min_count=1)),
        cnt_v=("v", "count"), min_i=("i", "min"), max_i=("i", "max"),
        avg_v=("v", "mean"))
    want = want.sort_values(["k", "s"], na_position="first") \
        .reset_index(drop=True)
    assert len(got) == len(want), f"seed={seed}"
    np.testing.assert_allclose(got.sum_v.to_numpy(dtype=float),
                               want.sum_v.to_numpy(dtype=float),
                               rtol=1e-9, err_msg=f"seed={seed}")
    assert (got.cnt_v.to_numpy() == want.cnt_v.to_numpy()).all(), \
        f"seed={seed}"
    np.testing.assert_allclose(got.avg_v.to_numpy(dtype=float),
                               want.avg_v.to_numpy(dtype=float),
                               rtol=1e-9, err_msg=f"seed={seed}")


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_sort(seed):
    rng = np.random.default_rng(seed + 100)
    n = int(rng.integers(500, 8000))
    t = _rand_table(rng, n)
    desc = bool(rng.integers(0, 2))
    nulls_first = bool(rng.integers(0, 2))
    plan = SortExec(
        MemoryScanExec.from_arrow(t, batch_rows=int(rng.integers(64, 512))),
        [(col(0, "k"), desc, nulls_first), (col(2, "i"), False, True)])
    got = _collect(plan)
    df = t.to_pandas()
    want = df.sort_values(
        ["k", "i"], ascending=[not desc, True],
        na_position="first" if nulls_first else "last",
        kind="stable").reset_index(drop=True)
    # pandas sorts nulls per-column; restrict the check to the primary key
    np.testing.assert_array_equal(
        got.k.to_numpy(dtype=float), want.k.to_numpy(dtype=float),
        err_msg=f"seed={seed} desc={desc} nf={nulls_first}")


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("jt", [JoinType.INNER, JoinType.LEFT,
                                JoinType.FULL, JoinType.LEFT_SEMI,
                                JoinType.LEFT_ANTI])
def test_fuzz_joins_smj_equals_shj(seed, jt):
    rng = np.random.default_rng(seed + 200)
    nl = int(rng.integers(200, 3000))
    nr = int(rng.integers(200, 3000))
    kr = int(rng.integers(5, 200))
    lt = _rand_table(rng, nl, with_decimal=False, key_range=kr)
    rt = _rand_table(rng, nr, with_decimal=False, key_range=kr)
    rt = rt.rename_columns(["rk", "rv", "ri", "rs"])
    mk = lambda cls: cls(
        MemoryScanExec.from_arrow(lt, batch_rows=int(rng.integers(64, 512))),
        MemoryScanExec.from_arrow(rt, batch_rows=int(rng.integers(64, 512))),
        [col(0)], [col(0)], jt)
    a = _collect(mk(SortMergeJoinExec))
    b = _collect(mk(ShuffledHashJoinExec))
    assert len(a) == len(b), f"seed={seed} jt={jt}"
    if len(a):
        cols = list(a.columns)
        a = a.sort_values(cols, na_position="first").reset_index(drop=True)
        b = b.sort_values(cols, na_position="first").reset_index(drop=True)
        pd.testing.assert_frame_equal(a, b, check_dtype=False,
                                      check_exact=False, atol=1e-9)


@pytest.mark.parametrize("seed", SEEDS[:2])
def test_fuzz_window_rank_and_running_sum(seed):
    from blaze_tpu.ops import WindowExec
    from blaze_tpu.ops.window import RankFunc, WindowAggFunc, WindowRankType
    rng = np.random.default_rng(seed + 300)
    n = int(rng.integers(300, 3000))
    t = pa.table({
        "p": pa.array(rng.integers(0, 20, n), type=pa.int64()),
        # unique order keys: ties make row_number/running sums
        # legitimately ambiguous between engines
        "o": pa.array(rng.permutation(n), type=pa.int64()),
        "v": pa.array(rng.random(n))})
    # the window contract takes (partition, order)-sorted input — the
    # converter puts a SortExec below every WindowExec
    sorted_in = SortExec(
        MemoryScanExec.from_arrow(t, batch_rows=int(rng.integers(64, 512))),
        [(col(0), False, True), (col(1), False, True)])
    plan = WindowExec(
        sorted_in,
        [RankFunc("rn", WindowRankType.ROW_NUMBER),
         WindowAggFunc("rs", make_agg("sum", [col(2)]), running=True)],
        [col(0)], [(col(1), False, True)])
    got = _collect(plan)
    df = t.to_pandas().sort_values(["p", "o"], kind="stable")
    df["rn"] = df.groupby("p").cumcount() + 1
    df["rs"] = df.groupby("p").v.cumsum()
    got = got.sort_values(["p", "o", "rn"], kind="stable") \
        .reset_index(drop=True)
    want = df.sort_values(["p", "o", "rn"], kind="stable") \
        .reset_index(drop=True)
    assert (got.rn.to_numpy() == want.rn.to_numpy()).all(), f"seed={seed}"
    np.testing.assert_allclose(got.rs.to_numpy(), want.rs.to_numpy(),
                               rtol=1e-9, err_msg=f"seed={seed}")
