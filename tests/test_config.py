"""Config schema parity tests (ref SparkAuronConfiguration.java, ~70 keys,
and SparkAuronConfigurationDocGenerator)."""

import pytest

from blaze_tpu import config

# the reference's full key list (SparkAuronConfiguration.java withKey calls)
REFERENCE_KEYS = [
    "auron.enabled", "auron.ui.enabled",
    "auron.process.vmrss.memoryFraction",
    "auron.enable.caseconvert.functions",
    "auron.enableInputBatchStatistics",
    "auron.udafFallback.enable", "auron.suggested.udaf.memUsedSize",
    "auron.udafFallback.num.udafs.trigger.sortAgg",
    "auron.udafFallback.typedImperativeEstimatedRowSize",
    "auron.cast.trimString", "auron.files.ignoreCorruptFiles",
    "auron.partialAggSkipping.enable", "auron.partialAggSkipping.ratio",
    "auron.partialAggSkipping.minRows",
    "auron.partialAggSkipping.skipSpill",
    "auron.parquet.enable.pageFiltering",
    "auron.parquet.enable.bloomFilter", "auron.parquet.maxOverReadSize",
    "auron.parquet.metadataCacheSize", "io.compression.codec",
    "io.compression.zstd.level", "auron.forceShuffledHashJoin",
    "auron.spill.compression.codec", "auron.smjfallback.enable",
    "auron.smjfallback.rows.threshold", "auron.smjfallback.mem.threshold",
    "auron.onHeapSpill.memoryFraction", "auron.parseJsonError.fallback",
    "auron.suggested.batch.memSize.multiwayMerging",
    "auron.orc.force.positional.evolution",
    "auron.orc.timestamp.use.microsecond",
    "auron.orc.schema.caseSensitive.enable",
    "auron.forceShortCircuitAndOr",
    "auron.udf.UDFJson.enabled", "auron.udf.brickhouse.enabled",
    "auron.decimal.arithOp.enabled", "auron.datetime.extract.enabled",
    "auron.udf.singleChildFallback.enabled",
] + [f"auron.enable.{op}" for op in (
    "scan", "paimon.scan", "iceberg.scan", "hudi.scan", "project",
    "filter", "sort", "union", "smj", "shj", "native.join.condition",
    "bhj", "bnlj", "local.limit", "global.limit",
    "take.ordered.and.project", "collectLimit", "aggr", "expand",
    "window", "window.group.limit", "generate", "local.table.scan",
    "data.writing", "data.writing.parquet", "data.writing.orc",
    "scan.parquet", "scan.parquet.timestamp", "scan.orc",
    "scan.orc.timestamp", "broadcastExchange", "shuffleExchange")]


def test_every_reference_key_is_defined():
    defined = {o["key"] for o in config.describe_all()}
    for o in config.describe_all():
        defined.update(o["alt_keys"])
    missing = [k for k in REFERENCE_KEYS if k not in defined]
    assert not missing, f"missing reference keys: {missing}"


def test_key_count_at_parity():
    assert len(config.describe_all()) >= 70


def test_all_keys_documented():
    undocumented = [o["key"] for o in config.describe_all() if not o["doc"]]
    assert not undocumented


def test_doc_generator_renders_markdown():
    md = config.generate_docs()
    assert md.startswith("# Configuration")
    for o in config.describe_all():
        assert f"`{o['key']}`" in md


def test_alt_keys_resolve():
    config.conf.set("auron.ignore.corrupted.files", True)  # legacy name
    try:
        assert config.IGNORE_CORRUPTED_FILES.get() is True
    finally:
        config.conf.unset("auron.ignore.corrupted.files")


def test_operator_enabled_lookup():
    assert config.operator_enabled("smj") is True
    config.conf.set("auron.enable.smj", False)
    try:
        assert config.operator_enabled("smj") is False
    finally:
        config.conf.unset("auron.enable.smj")
    assert config.operator_enabled("not.a.real.op") is True


def test_skip_spill_switches_partial_agg_to_passthrough():
    """auron.partialAggSkipping.skipSpill: under pressure the partial agg
    passes rows through instead of spilling, and a final stage repairs."""
    import numpy as np
    import pyarrow as pa
    from blaze_tpu.exprs import col
    from blaze_tpu.memory import MemManager
    from blaze_tpu.ops import AggExec, AggMode, MemoryScanExec, make_agg
    from blaze_tpu.shuffle import HashPartitioning, LocalShuffleExchange

    rng = np.random.default_rng(0)
    n = 60_000
    t = pa.table({"k": pa.array(rng.integers(0, 5000, n)),
                  "v": pa.array(rng.random(n))})
    config.conf.set(config.PARTIAL_AGG_SKIPPING_SKIP_SPILL.key, True)
    MemManager.init(128 << 10)
    try:
        partial = AggExec(MemoryScanExec.from_arrow(t, batch_rows=4096),
                          [(col(0, "k"), "k")],
                          [(make_agg("sum", [col(1)]), AggMode.PARTIAL,
                            "s")])
        ex = LocalShuffleExchange(partial, HashPartitioning([col(0)], 1))
        final = AggExec(ex, [(col(0, "k"), "k")],
                        [(make_agg("sum", [col(1)]),
                          AggMode.PARTIAL_MERGE, "s")])
        out = pa.Table.from_batches(
            [b.compact().to_arrow() for b in final.execute(0)]).to_pandas()
        assert partial.metrics.get("partial_skipped") >= 1
        assert partial.metrics.get("spill_count") == 0
    finally:
        config.conf.unset(config.PARTIAL_AGG_SKIPPING_SKIP_SPILL.key)
        MemManager.init(4 << 30)
    want = t.to_pandas().groupby("k").v.sum().reset_index()
    got = out.sort_values("k").reset_index(drop=True)
    np.testing.assert_allclose(got["s.sum"].to_numpy(),
                               want.sort_values("k").v.to_numpy(),
                               rtol=1e-9)
