"""Dict-code device strategy for var-width group keys (VERDICT r4 #8 /
SURVEY §7 hard-part #1): utf8 keys dictionary-encode to dense i32 codes,
the device groups by packed code ids through the sort-free dense kernel,
and keys decode back through the accumulated dictionaries at emit.

Host-vectorized aggregation is disabled throughout so the dict-device
branch (the DEVICE-placement path for string keys) is what actually runs."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.bridge.resource import put_resource
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan
from blaze_tpu.plan.fused import FusedPartialAggExec, fuse_plan
from blaze_tpu.plan.types import schema_to_dict
from blaze_tpu.schema import Schema


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _scan(rid, table):
    put_resource(rid, table)
    return {"kind": "memory_scan", "resource_id": rid,
            "schema": schema_to_dict(Schema.from_arrow(table.schema)),
            "num_partitions": 1}


def _agg_ir(scan, mode="complete"):
    # min/max run over the INT column: float min/max args are refused by
    # the dict-device admission (NaN total-order semantics) by design
    c = lambda i: {"kind": "column", "index": i}  # noqa: E731
    return {"kind": "hash_agg",
            "groupings": [{"expr": c(0), "name": "k"},
                          {"expr": c(1), "name": "g"}],
            "aggs": [{"fn": "sum", "mode": mode, "name": "s",
                      "args": [c(2)]},
                     {"fn": "count", "mode": mode, "name": "c",
                      "args": [c(2)]},
                     {"fn": "min", "mode": mode, "name": "mn",
                      "args": [c(3)]},
                     {"fn": "max", "mode": mode, "name": "mx",
                      "args": [c(3)]}],
            "input": scan}


def _run_dict_device(table, mode="complete", batch_size=None,
                     max_slots=None):
    kv = {"auron.tpu.fused.hostVectorized": "false"}
    if batch_size:
        kv["auron.batch.size"] = str(batch_size)
    if max_slots:
        kv["auron.tpu.fused.dictDevice.maxSlots"] = str(max_slots)
    with config.scoped(**kv):
        node = fuse_plan(create_plan(_agg_ir(_scan("dictdev://t", table),
                                             mode)))
        assert isinstance(node, FusedPartialAggExec)
        out = pa.Table.from_batches(
            [b.compact().to_arrow() for b in node.execute(0)])
        return out, node.collect_metrics()


def _oracle(keys, ints, vals, w=None):
    w = ints if w is None else w
    want = (pd.DataFrame({"k": keys, "g": ints, "v": vals, "w": w})
            .groupby(["k", "g"], dropna=False)
            .agg(s=("v", "sum"), c=("v", "count"), mn=("w", "min"),
                 mx=("w", "max")).reset_index())
    want["k"] = want["k"].fillna("<NULL>")
    return want.sort_values(["k", "g"]).reset_index(drop=True)


def _check(out, keys, ints, vals, w=None):
    got = out.to_pandas()
    got["k"] = got["k"].fillna("<NULL>")
    got = got.sort_values(["k", "g"]).reset_index(drop=True)
    want = _oracle(keys, ints, vals, w)
    assert len(got) == len(want)
    np.testing.assert_array_equal(got["k"].values, want["k"].values)
    np.testing.assert_array_equal(got["g"].values.astype("int64"),
                                  want["g"].values.astype("int64"))
    np.testing.assert_allclose(got["s"].values, want["s"].values,
                               rtol=1e-12)
    np.testing.assert_array_equal(got["c"].values.astype("int64"),
                                  want["c"].values.astype("int64"))
    np.testing.assert_allclose(got["mn"].values, want["mn"].values)
    np.testing.assert_allclose(got["mx"].values, want["mx"].values)


def _make(n, n_keys, seed=3, nulls=50):
    rng = np.random.default_rng(seed)
    keys = [f"cust_{i:04d}" for i in rng.integers(0, n_keys, n)]
    for j in rng.integers(0, n, nulls):
        keys[j] = None
    ints = rng.integers(0, 7, n)
    vals = rng.random(n)
    w = rng.integers(-1000, 1000, n)
    return keys, ints, vals, w


def test_single_batch_exact():
    keys, ints, vals, w = _make(5000, 700)
    t = pa.table({"k": pa.array(keys), "g": pa.array(ints),
                  "v": pa.array(vals), "w": pa.array(w)})
    out, m = _run_dict_device(t)
    assert m.get("dict_device_batches")
    _check(out, keys, ints, vals, w)


def test_multi_batch_dictionary_growth_relayout():
    """Keys arrive in waves: the first batches see a handful of distinct
    strings (small capacity), later batches push the dictionary past
    successive power-of-two capacities — the table re-lays out without
    losing or double-counting a single group."""
    rng = np.random.default_rng(11)
    parts = []
    for wave, hi in enumerate([8, 60, 900]):
        kk = [f"cust_{i:04d}" for i in rng.integers(0, hi, 2000)]
        parts.append(kk)
    keys = [k for p in parts for k in p]
    n = len(keys)
    ints = rng.integers(0, 7, n)
    vals = rng.random(n)
    w = rng.integers(-1000, 1000, n)
    t = pa.table({"k": pa.array(keys), "g": pa.array(ints),
                  "v": pa.array(vals), "w": pa.array(w)})
    out, m = _run_dict_device(t, batch_size=512)
    assert m.get("dict_device_batches") >= 10  # really multi-batch
    _check(out, keys, ints, vals, w)


def test_partial_mode_acc_columns():
    """PARTIAL mode emits acc columns the reduce side re-merges — the
    dict-device table must produce the same partials as the host path."""
    keys, ints, vals, w = _make(3000, 300, seed=5)
    t = pa.table({"k": pa.array(keys), "g": pa.array(ints),
                  "v": pa.array(vals), "w": pa.array(w)})
    out, m = _run_dict_device(t, mode="partial")
    assert m.get("dict_device_batches")
    # partial of sum/count over disjoint groups == complete values here
    got_rows = out.num_rows
    want = _oracle(keys, ints, vals)
    assert got_rows == len(want)
    assert float(pa.compute.sum(out.column(2)).as_py()) == \
        pytest.approx(float(np.sum(vals)), rel=1e-12)


def test_max_slots_falls_back_to_host():
    keys, ints, vals, w = _make(4000, 2000, seed=9, nulls=0)
    t = pa.table({"k": pa.array(keys), "g": pa.array(ints),
                  "v": pa.array(vals), "w": pa.array(w)})
    out, m = _run_dict_device(t, max_slots=256)
    assert m.get("dict_device_fallback") == 1
    _check(out, keys, ints, vals, w)


def test_all_null_and_empty_batches():
    keys = [None] * 257
    ints = np.zeros(257, dtype=np.int64)
    vals = np.ones(257)
    t = pa.table({"k": pa.array(keys, pa.utf8()),
                  "g": pa.array(ints), "v": pa.array(vals),
                  "w": pa.array(np.arange(257))})
    out, _m = _run_dict_device(t)
    assert out.num_rows == 1
    assert out.column("k").to_pylist() == [None]
    assert out.column("c").to_pylist() == [257]


def test_float_key_normalization_nan_negzero():
    """Float group keys normalize like Spark's NormalizeFloatingNumbers:
    every NaN bit pattern is one group, and -0.0 groups with 0.0."""
    nan = float("nan")
    t = pa.table({"k": pa.array([nan, nan, -0.0, 0.0, 1.0]),
                  "g": pa.array([0, 0, 0, 0, 0]),
                  "v": pa.array([1.0, 2.0, 4.0, 8.0, 16.0]),
                  "w": pa.array([1, 2, 3, 4, 5])})
    with config.scoped(**{"auron.tpu.fused.hostVectorized": "false"}):
        node = fuse_plan(create_plan(_agg_ir(_scan("dictdev://f", t))))
        assert isinstance(node, FusedPartialAggExec)
        out = pa.Table.from_batches(
            [b.compact().to_arrow() for b in node.execute(0)])
    sums = {}
    for k, s in zip(out.column("k").to_pylist(),
                    out.column("s").to_pylist()):
        sums["nan" if k != k else k] = s
    assert sums["nan"] == 3.0     # both NaNs in ONE group
    assert sums[0.0] == 12.0      # -0.0 and 0.0 in ONE group
    assert sums[1.0] == 16.0
    assert out.num_rows == 3


def test_min_max_float_args_not_fused_to_dict_device():
    """min/max over FLOAT args must not be claimed by the dict-device
    path — its jnp.minimum fold propagates NaN where Spark skips it.
    The plan stays an AggExec (exact semantics) instead."""
    t = pa.table({"k": pa.array(["a"]), "g": pa.array([1]),
                  "v": pa.array([1.0])})
    c = lambda i: {"kind": "column", "index": i}  # noqa: E731
    ir = {"kind": "hash_agg",
          "groupings": [{"expr": c(0), "name": "k"}],
          "aggs": [{"fn": "min", "mode": "complete", "name": "mn",
                    "args": [c(2)]}],
          "input": _scan("dictdev://mm", t)}
    with config.scoped(**{"auron.tpu.fused.hostVectorized": "false"}):
        node = fuse_plan(create_plan(ir))
        # min over the float v -> not fused (NaN total order)
        assert not isinstance(node, FusedPartialAggExec)


def test_selective_filter_does_not_grow_dictionary():
    """Deselected rows must not enter the dictionary: a 1%-selective
    filter over a high-cardinality utf8 column keeps the code table at
    the SELECTED cardinality instead of tripping maxSlots."""
    rng = np.random.default_rng(21)
    n = 4000
    keys = [f"k_{i:05d}" for i in range(n)]      # all distinct
    flag = (rng.random(n) < 0.02).astype(np.int64)
    vals = rng.random(n)
    t = pa.table({"k": pa.array(keys), "f": pa.array(flag),
                  "v": pa.array(vals)})
    c = lambda i: {"kind": "column", "index": i}  # noqa: E731
    ir = {"kind": "hash_agg",
          "groupings": [{"expr": c(0), "name": "k"}],
          "aggs": [{"fn": "sum", "mode": "complete", "name": "s",
                    "args": [c(2)]}],
          "input": {"kind": "filter",
                    "predicates": [{"kind": "binary", "op": "==",
                                    "l": c(1),
                                    "r": {"kind": "literal", "value": 1,
                                          "type": {"id": "int64"}}}],
                    "input": _scan("dictdev://sel", t)}}
    with config.scoped(**{"auron.tpu.fused.hostVectorized": "false",
                          "auron.tpu.fused.dictDevice.maxSlots": "2048"}):
        node = fuse_plan(create_plan(ir))
        assert isinstance(node, FusedPartialAggExec)
        out = pa.Table.from_batches(
            [b.compact().to_arrow() for b in node.execute(0)])
        m = node.collect_metrics()
    # 4000 distinct raw keys would exceed maxSlots=2048; the ~80
    # selected ones must not
    assert not m.get("dict_device_fallback")
    want = {k: v for k, f, v in zip(keys, flag, vals) if f}
    got = dict(zip(out.column("k").to_pylist(),
                   out.column("s").to_pylist()))
    assert got.keys() == want.keys()
    for k in want:
        assert got[k] == pytest.approx(want[k], rel=1e-12)


def test_placement_drift_with_float_minmax_raises_loudly():
    """A plan fused for the HOST path (min over float is Arrow-eligible
    there) whose config drifts before execute must raise, not run the
    NaN-propagating dict-device fold silently."""
    t = pa.table({"k": pa.array(["a", "a"]), "g": pa.array([1, 1]),
                  "v": pa.array([float("nan"), 3.0])})
    c = lambda i: {"kind": "column", "index": i}  # noqa: E731
    ir = {"kind": "hash_agg",
          "groupings": [{"expr": c(0), "name": "k"}],
          "aggs": [{"fn": "min", "mode": "complete", "name": "mn",
                    "args": [c(2)]}],
          "input": _scan("dictdev://drift", t)}
    # float min/max with var-width keys is refused by BOTH admission
    # paths (host eligibility and dict_ok), so fuse_plan never builds
    # this node — construct it directly to exercise the runtime
    # defense-in-depth guard that a drifted/hand-built plan hits
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggMode, make_agg
    agg_plan = create_plan(ir)
    mn = make_agg("min", [col(2, "v")])
    node = FusedPartialAggExec(
        agg_plan.children[0], [(col(0, "k"), "k")],
        [(mn, AggMode.COMPLETE, "mn")],
        [("min", "min", col(2, "v"))], ranges=None, complete=True)
    with config.scoped(**{"auron.tpu.fused.hostVectorized": "false"}):
        with pytest.raises(RuntimeError, match="host placement"):
            list(node.execute(0))
