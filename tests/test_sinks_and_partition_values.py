"""VERDICT r2 #10 parity holes: OrcSinkExec coverage and partition-
constant columns riding the proto wire (ref orc_sink_exec.rs:568,
planner.rs:170-200 FileScanExecConf partition values)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan
from blaze_tpu.plan.proto_serde import (plan_from_proto, plan_to_proto,
                                        task_definition_to_bytes)


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def test_orc_sink_roundtrip(tmp_path):
    from pyarrow import orc
    t = pa.table({"k": pa.array([3, 1, 2], type=pa.int64()),
                  "s": pa.array(["c", "a", "b"])})
    src = str(tmp_path / "in.parquet")
    pq.write_table(t, src)
    out = str(tmp_path / "orc_out")
    ir = {"kind": "orc_sink", "path": out,
          "input": {"kind": "parquet_scan",
                    "schema": {"fields": [
                        {"name": "k", "type": {"id": "int64"},
                         "nullable": True},
                        {"name": "s", "type": {"id": "utf8"},
                         "nullable": True}]},
                    "file_groups": [[src]]}}
    plan = create_plan(ir)
    list(plan.execute(0))
    files = sorted((tmp_path / "orc_out").iterdir())
    assert len(files) == 1 and files[0].suffix == ".orc"
    back = orc.read_table(str(files[0]))
    assert back.equals(t)
    # and the sink rides the proto wire
    decoded = plan_from_proto(plan_to_proto(ir))
    assert decoded["kind"] == "orc_sink"


def test_partition_values_over_proto_wire(tmp_path):
    """Hive-partitioned scan: the file carries (k, v); partition columns
    (p_date) are constants attached per file — the connector-scan shape
    that previously could not ride the wire."""
    t = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                  "v": pa.array([10.0, 20.0])})
    src = str(tmp_path / "part0.parquet")
    pq.write_table(t, src)
    ir = {"kind": "parquet_scan",
          "schema": {"fields": [
              {"name": "k", "type": {"id": "int64"}, "nullable": True},
              {"name": "v", "type": {"id": "float64"},
               "nullable": True}]},
          "partition_schema": {"fields": [
              {"name": "p_state", "type": {"id": "utf8"},
               "nullable": True},
              {"name": "p_year", "type": {"id": "int64"},
               "nullable": True}]},
          "partition_values": [[["CA", 2001]]],
          "file_groups": [[src]]}

    # direct execution appends the constants
    got = pa.Table.from_batches(
        [b.compact().to_arrow() for b in create_plan(ir).execute(0)])
    assert got.column_names == ["k", "v", "p_state", "p_year"]
    assert got.column("p_state").to_pylist() == ["CA", "CA"]
    assert got.column("p_year").to_pylist() == [2001, 2001]

    # proto round trip preserves schema + values
    decoded = plan_from_proto(plan_to_proto(ir))
    assert decoded["partition_values"] == [[["CA", 2001]]]
    assert [f["name"] for f in decoded["partition_schema"]["fields"]] == \
        ["p_state", "p_year"]

    # and the full TaskDefinition wire executes
    from blaze_tpu.bridge.runtime import NativeExecutionRuntime
    td = task_definition_to_bytes(
        {"stage_id": 0, "partition_id": 0, "num_partitions": 1,
         "plan": ir})
    rt = NativeExecutionRuntime(td).start()
    try:
        rows = list(rt.batches())
    finally:
        rt.finalize()
    wired = pa.Table.from_batches(rows)
    assert wired.column("p_year").to_pylist() == [2001, 2001]


def test_projection_selects_partition_columns_in_order(tmp_path):
    """Reference FileScanExecConf semantics (ADVICE r3 #1): projection
    indices address file schema + partition schema COMBINED, output is
    exactly the projected columns in projection order — a plan projecting
    one partition column must not gain trailing extras, and one projecting
    none must emit file columns only."""
    t = pa.table({"k": pa.array([1, 2], type=pa.int64()),
                  "v": pa.array([1.5, 2.5])})
    src = str(tmp_path / "part.parquet")
    pq.write_table(t, src)
    base = {"kind": "parquet_scan",
            "schema": {"fields": [
                {"name": "k", "type": {"id": "int64"}, "nullable": True},
                {"name": "v", "type": {"id": "float64"}, "nullable": True}]},
            "partition_schema": {"fields": [
                {"name": "region", "type": {"id": "utf8"}, "nullable": True},
                {"name": "year", "type": {"id": "int64"}, "nullable": True}]},
            "partition_values": [[["CA", 2001]]],
            "file_groups": [[src]]}

    # interleaved projection incl. ONE partition column
    ir = dict(base, projection=["year", "k"])
    rt = plan_from_proto(plan_to_proto(ir))
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in create_plan(rt).execute(0)])
    assert out.column_names == ["year", "k"]
    assert out.column("year").to_pylist() == [2001, 2001]
    assert out.column("k").to_pylist() == [1, 2]

    # projection of file columns only: NO trailing partition columns
    ir2 = dict(base, projection=["v"])
    rt2 = plan_from_proto(plan_to_proto(ir2))
    out2 = pa.Table.from_batches(
        [b.compact().to_arrow() for b in create_plan(rt2).execute(0)])
    assert out2.column_names == ["v"]

    # no projection: file columns + ALL partition columns (default)
    rt3 = plan_from_proto(plan_to_proto(base))
    out3 = pa.Table.from_batches(
        [b.compact().to_arrow() for b in create_plan(rt3).execute(0)])
    assert out3.column_names == ["k", "v", "region", "year"]
    assert out3.column("region").to_pylist() == ["CA", "CA"]
