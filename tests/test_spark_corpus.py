"""Spark-semantics conformance corpus (VERDICT r3 missing #5 — the
auron-spark-tests tier analog).  Every vendored vector must pass, and
every exclusion must carry a reason (the declared-divergence ledger)."""

import pytest

from blaze_tpu.itest.spark_corpus import (SUITES, default_settings,
                                          run_case, run_corpus)
from blaze_tpu.memory import MemManager


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


_ALL = [(s, c.name) for s, cases in sorted(SUITES.items())
        for c in cases]


@pytest.mark.parametrize("suite,case_name", _ALL,
                         ids=[f"{s}::{n}" for s, n in _ALL])
def test_corpus_case(suite, case_name):
    settings = default_settings()
    ss = settings.suites[suite]
    if not ss.selects(case_name):
        pytest.skip(f"excluded: {ss.excluded.get(case_name, '')}")
    case = next(c for c in SUITES[suite] if c.name == case_name)
    res = run_case(suite, case)
    assert res.passed, f"{suite}::{case_name}: {res.detail}"


def test_exclusions_carry_reasons():
    settings = default_settings()
    for ss in settings.suites.values():
        for name, reason in ss.excluded.items():
            assert reason, f"{ss.name}::{name} excluded without a reason"


def test_dsl_include_exclude():
    from blaze_tpu.itest.spark_corpus import CorpusSettings
    s = CorpusSettings()
    st = s.enable_suite("MathSuite").include_by_prefix("round")
    st.exclude("round is HALF_UP away from zero", reason="demo")
    picked = [r.case for r in run_corpus(s)]
    assert picked == []  # the only round-prefixed case was excluded
