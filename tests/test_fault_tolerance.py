"""Fault-tolerance tests (ISSUE 4): deterministic injection, bounded
task retry, shuffle CRC32C integrity, and lineage recovery that re-runs
ONLY the poisoned producer map task — with bit-identical results."""

import io
import os
import struct
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.bridge.tasks import run_tasks
from blaze_tpu.faults import (FaultInjector, FetchFailedError, InjectedFault,
                              ShuffleChecksumError, classify_exception,
                              parse_rules)
from blaze_tpu.memory import MemManager
from blaze_tpu.memory.manager import MemConsumer
from blaze_tpu.plan.stages import DagScheduler
from blaze_tpu.shuffle.exchange import read_index_file
from blaze_tpu.shuffle.ipc import (FLAG_CRC, IpcCompressionReader,
                                   IpcCompressionWriter,
                                   read_batches_from_bytes,
                                   write_batches_to_bytes)


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    try:
        yield
    finally:
        faults.clear()


@pytest.fixture
def fast_retries():
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 1)
    try:
        yield
    finally:
        config.conf.unset(config.TASK_RETRY_BACKOFF_MS.key)


@pytest.fixture
def staged_path():
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


# -- injector ---------------------------------------------------------------

def test_injector_deterministic_fire_sequence():
    def sequence():
        inj = FaultInjector(seed=42)
        inj.install("task-start", p=0.3)
        return [inj.decide("task-start") is not None for _ in range(200)]

    a, b = sequence(), sequence()
    assert a == b
    assert any(a) and not all(a)  # p=0.3 fires some, not all


def test_injector_explicit_occurrences_and_cap():
    inj = FaultInjector(seed=0)
    inj.install("shuffle-read", at=(2, 5))
    fired = [k for k in range(1, 8)
             if inj.decide("shuffle-read") is not None]
    assert fired == [2, 5]
    inj2 = FaultInjector(seed=7)
    inj2.install("ipc-decode", p=1.0, times=3)
    assert sum(inj2.decide("ipc-decode") is not None
               for _ in range(10)) == 3


def test_parse_rules_grammar():
    rules = parse_rules(
        "task-start=0.25,shuffle-write@1+4:corrupt,ipc-decode=0.1*2")
    assert rules[0] == ("task-start",
                        dict(p=0.25, times=None, action="raise"))
    assert rules[1] == ("shuffle-write",
                        dict(at=(1, 4), times=None, action="corrupt"))
    assert rules[2] == ("ipc-decode",
                        dict(p=0.1, times=2, action="raise"))
    with pytest.raises(ValueError):
        parse_rules("task-start")


def test_scoped_injection_restores_previous_state():
    assert faults.stats() == {}
    with faults.scoped(("task-start", dict(at=(1,)))):
        with pytest.raises(InjectedFault):
            faults.maybe_fail("task-start")
    faults.maybe_fail("task-start")  # injector gone: no-op


def test_classify_exception():
    assert classify_exception(InjectedFault("x")) == "retryable"
    assert classify_exception(ShuffleChecksumError("x")) == "retryable"
    assert classify_exception(EOFError()) == "retryable"
    assert classify_exception(OSError("io")) == "retryable"
    assert classify_exception(FetchFailedError(1, 2, "x")) == "fetch-failed"
    assert classify_exception(ValueError("plan")) == "fatal"
    assert classify_exception(MemoryError()) == "fatal"


# -- frame integrity --------------------------------------------------------

def _batch(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.record_batch({"a": pa.array(rng.integers(0, 100, n)),
                            "b": pa.array(rng.random(n))})


def test_checksum_roundtrip_and_flag_bit():
    data = write_batches_to_bytes([_batch()])
    assert data[0] & FLAG_CRC  # v2 frame: checksum flag set
    got = list(read_batches_from_bytes(data))
    assert sum(b.num_rows for b in got) == 1000


def test_bit_flip_detected():
    data = bytearray(write_batches_to_bytes([_batch()]))
    data[len(data) // 2] ^= 0x01  # flip one payload bit
    with pytest.raises(ShuffleChecksumError, match="CRC32C mismatch"):
        list(read_batches_from_bytes(bytes(data)))


def test_legacy_unchecksummed_frames_still_read():
    sink = io.BytesIO()
    w = IpcCompressionWriter(sink, checksum=False)
    w.write_batch(_batch())
    w.finish()
    data = sink.getvalue()
    assert not data[0] & FLAG_CRC
    got = list(read_batches_from_bytes(data))
    assert sum(b.num_rows for b in got) == 1000


def test_unknown_codec_byte_rejected():
    data = bytearray(write_batches_to_bytes([_batch()]))
    data[0] = 0x7F  # unknown codec id, flags clear
    with pytest.raises(ShuffleChecksumError, match="unknown shuffle frame"):
        list(read_batches_from_bytes(bytes(data)))


def test_truncated_checksum_frame():
    data = write_batches_to_bytes([_batch()])
    with pytest.raises(EOFError):
        list(IpcCompressionReader(io.BytesIO(data[:4])).read_batches())


def test_injected_corruption_caught_by_crc():
    with faults.scoped(("shuffle-write", dict(at=(1,), action="corrupt"))):
        data = write_batches_to_bytes([_batch()])
    with pytest.raises(ShuffleChecksumError):
        list(read_batches_from_bytes(data))


# -- index validation -------------------------------------------------------

def test_read_index_file_validation(tmp_path):
    data_file = str(tmp_path / "x.data")
    with open(data_file, "wb") as f:
        f.write(b"\0" * 100)

    def write_index(offsets, raw=None):
        p = str(tmp_path / "x.index")
        with open(p, "wb") as f:
            f.write(raw if raw is not None
                    else struct.pack(f"<{len(offsets)}q", *offsets))
        return p

    ok = write_index([0, 40, 100])
    assert read_index_file(ok, expected_partitions=2,
                           data_file=data_file) == [0, 40, 100]
    with pytest.raises(FetchFailedError, match="whole number"):
        read_index_file(write_index([], raw=b"\0" * 7))
    with pytest.raises(FetchFailedError, match="truncated index"):
        read_index_file(write_index([0, 100]), expected_partitions=2)
    with pytest.raises(FetchFailedError, match="monotone"):
        read_index_file(write_index([0, 60, 40]))
    with pytest.raises(FetchFailedError, match="exceeds data"):
        read_index_file(write_index([0, 40, 101]), data_file=data_file)
    with pytest.raises(FetchFailedError, match="!= 0"):
        read_index_file(write_index([8, 40, 100]))


# -- task pool --------------------------------------------------------------

def test_retry_then_succeed(fast_retries):
    xla_stats.reset()
    with faults.scoped(("task-start", dict(at=(1,)))):
        out = run_tasks(lambda i: i * 10, 1, 30.0, "retry-test")
    assert out == [0]
    fs = xla_stats.fault_stats()
    assert fs["task_retries"] == 1
    assert fs["task_attempts"] == 2
    assert fs["task_failures"] == 0
    assert fs["faults_injected"] == 1


def test_retryable_exhaustion_fails(fast_retries):
    config.conf.set(config.TASK_MAX_ATTEMPTS.key, 3)
    try:
        calls = []
        with pytest.raises(OSError):
            run_tasks(lambda i: calls.append(i) or (_ for _ in ()).throw(
                OSError("flaky disk")), 1, 30.0, "exhaust-test")
        assert len(calls) == 3  # maxAttempts honored
    finally:
        config.conf.unset(config.TASK_MAX_ATTEMPTS.key)


def test_fatal_error_not_retried(fast_retries):
    calls = []

    def boom(i):
        calls.append(i)
        raise ValueError("bad plan")

    with pytest.raises(ValueError):
        run_tasks(boom, 1, 30.0, "fatal-test")
    assert calls == [0]  # exactly one attempt


def test_first_exception_fails_fast():
    def fn(i):
        if i == 0:
            raise ValueError("instant failure")
        time.sleep(5.0)

    t0 = time.monotonic()
    with pytest.raises(ValueError, match="instant failure"):
        run_tasks(fn, 2, 30.0, "fast-fail-test", max_workers=2)
    # the old wait(...) semantics sat out the slowest sibling (5s);
    # FIRST_EXCEPTION must surface the failure immediately
    assert time.monotonic() - t0 < 3.0


def test_fetch_failed_preferred_over_sibling_errors():
    def fn(i):
        if i == 0:
            raise ValueError("sibling noise")
        time.sleep(0.2)
        raise FetchFailedError(0, 1, "poisoned block")

    with pytest.raises((FetchFailedError, ValueError)) as ei:
        run_tasks(fn, 2, 30.0, "prefer-test", max_workers=2)
    # both orderings are legal depending on scheduling; when the fetch
    # failure is visible in the same wait round it must win
    if isinstance(ei.value, FetchFailedError):
        assert ei.value.map_id == 1


# -- mem-pressure site ------------------------------------------------------

def test_mem_pressure_fault_forces_spill():
    class Probe(MemConsumer):
        def __init__(self):
            super().__init__("probe")
            self.spills = 0

        def spill(self):
            self.spills += 1
            released = self._mem_used
            self._mem_used = 0
            return released

    mm = MemManager.init(1 << 30)
    probe = Probe()
    probe.set_spillable(mm)
    try:
        probe.update_mem_used(1 << 20)  # far under budget: no spill
        assert probe.spills == 0
        with faults.scoped(("mem-pressure", dict(at=(1,)))):
            probe.add_mem_used(1 << 20)
        assert probe.spills == 1
    finally:
        probe.unregister()


# -- staged execution: lineage recovery -------------------------------------

def _two_stage_plan(tmp_path, n=20_000, n_reduce=3):
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 200, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"in-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}


def _sorted_df(tbl):
    return tbl.to_pandas().sort_values("k").reset_index(drop=True)


def test_corrupted_block_recovers_bit_identical(tmp_path, staged_path,
                                                fast_retries):
    plan = _two_stage_plan(tmp_path)
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag0")).run_collect(plan))

    xla_stats.reset()
    # corrupt the FIRST frame any map task flushes: under serial host
    # execution that is map task 0's output, so exactly stage 0 / map 0
    # must be re-run — and nothing else
    with faults.scoped(("shuffle-write", dict(at=(1,), action="corrupt"))):
        sched = DagScheduler(work_dir=str(tmp_path / "dag1"))
        got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)  # bit-identical recovery
    assert sched.task_runs[(0, 0)] == 2  # poisoned map task re-ran...
    assert sched.task_runs[(0, 1)] == 1  # ...and ONLY that one
    fs = xla_stats.fault_stats()
    assert fs["fetch_failures"] >= 1
    assert fs["stage_recoveries"] == 1
    assert fs["recovered_map_tasks"] == 1
    assert fs["faults_injected"] == 1


def test_recovery_rounds_bounded(tmp_path, staged_path, fast_retries):
    plan = _two_stage_plan(tmp_path)
    config.conf.set(config.STAGE_MAX_RECOVERIES.key, 2)
    try:
        # EVERY frame corrupt: recovery re-runs can never produce a
        # clean block, so the scheduler must give up after the cap
        with faults.scoped(("shuffle-write",
                            dict(p=1.0, action="corrupt"))):
            with pytest.raises(FetchFailedError, match="gave up after 2"):
                DagScheduler(
                    work_dir=str(tmp_path / "dag")).run_collect(plan)
    finally:
        config.conf.unset(config.STAGE_MAX_RECOVERIES.key)


def test_injected_read_fault_recovers(tmp_path, staged_path, fast_retries):
    plan = _two_stage_plan(tmp_path)
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag0")).run_collect(plan))
    # a raise-action fault on the read side converts to FetchFailedError
    # (a fetch that failed, vs a block that decoded wrong) — same
    # recovery path, different entry point
    with faults.scoped(("shuffle-read", dict(at=(1,)))):
        got = _sorted_df(DagScheduler(
            work_dir=str(tmp_path / "dag1")).run_collect(plan))
    assert got.equals(clean)


def test_explain_analyze_reports_fault_tolerance(tmp_path, staged_path,
                                                 fast_retries):
    from blaze_tpu.plan.explain import QueryProfile
    xla_stats.reset()
    before = xla_stats.snapshot()
    plan = _two_stage_plan(tmp_path)
    with faults.scoped(("shuffle-write", dict(at=(1,), action="corrupt"))):
        sched = DagScheduler(work_dir=str(tmp_path / "dag"))
        sched.run_collect(plan)
    profile = QueryProfile(
        query_id="q-ft", wall_ns=1, tree=sched.collect_metrics(),
        partitions=3, exec_mode="staged", xla=xla_stats.delta(before),
        kernels={}, placement="host", output_rows=0)
    text = profile.render_text()
    assert "fault tolerance:" in text
    assert "recoveries=1" in text
    assert "faults_injected=1" in text


def test_cleanup_idempotent_and_context_manager(tmp_path, staged_path):
    from blaze_tpu.bridge.resource import get_resource
    plan = _two_stage_plan(tmp_path, n=4_000)
    with DagScheduler(work_dir=str(tmp_path / "dag")) as sched:
        sched.run_collect(plan)
        rids = [st.resource_id for st in sched.stages
                if st.resource_id is not None]
        assert rids
        # run_collect's finally already cleaned up: nothing leaked
        for rid in rids:
            assert get_resource(rid) is None
        sched.cleanup()  # idempotent: second call is a no-op
    sched.cleanup()      # ...and so is a third, after __exit__
    sched.__del__()      # __del__ backstop never raises


def test_faults_disabled_zero_overhead_counters(tmp_path, staged_path):
    """No injector: a staged run must report zero fault-tolerance
    activity (retries/recoveries stay out of steady-state runs)."""
    xla_stats.reset()
    plan = _two_stage_plan(tmp_path, n=4_000)
    DagScheduler(work_dir=str(tmp_path / "dag")).run_collect(plan)
    fs = xla_stats.fault_stats()
    assert fs["task_retries"] == 0
    assert fs["fetch_failures"] == 0
    assert fs["stage_recoveries"] == 0
    assert fs["faults_injected"] == 0
    assert fs["task_failures"] == 0


# -- device-shuffle fallback x lineage recovery (ISSUE 6) -------------------

@pytest.fixture
def device_shuffle_on():
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    try:
        yield
    finally:
        config.conf.unset(config.SHUFFLE_DEVICE.key)


def test_device_shuffle_falls_back_to_files_bit_identical(
        tmp_path, staged_path, fast_retries, device_shuffle_on):
    """A shard dying mid-collective must not fail the query: the stage
    falls back wholesale to the host file shuffle and produces the
    exact same bytes."""
    config.conf.set(config.SHUFFLE_DEVICE.key, "off")
    plan = _two_stage_plan(tmp_path, n=4_000)
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag0")).run_collect(plan))
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")

    xla_stats.reset()
    with faults.scoped(("device-collective", dict(at=(1,)))):
        sched = DagScheduler(work_dir=str(tmp_path / "dag1"))
        got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)
    ss = xla_stats.shuffle_stats()
    assert ss["shuffle_device_fallbacks"] == 1
    assert ss["shuffle_device_exchanges"] == 0  # collective never landed
    assert ss["shuffle_host_bytes"] > 0         # files took over
    # map tasks ran twice: once collecting for the device exchange,
    # once re-partitioning into shuffle files on the fallback path
    assert sched.task_runs[(0, 0)] == 2
    assert sched.task_runs[(0, 1)] == 2


def test_device_fallback_composes_with_lineage_recovery(
        tmp_path, staged_path, fast_retries, device_shuffle_on):
    """Worst case end-to-end: the collective dies AND the fallback's
    first shuffle file is corrupt.  PR 4's lineage recovery must kick
    in on the file path and still deliver bit-identical output."""
    config.conf.set(config.SHUFFLE_DEVICE.key, "off")
    plan = _two_stage_plan(tmp_path, n=4_000)
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag0")).run_collect(plan))
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")

    xla_stats.reset()
    # device-collective@1 kills the first shard touched by the first
    # dispatch; the device-collect map runs never hit shuffle-write, so
    # shuffle-write@1 corrupts the FIRST frame the fallback path
    # flushes — map task 0's output, exactly as in the pure-file test
    with faults.scoped(("device-collective", dict(at=(1,))),
                       ("shuffle-write", dict(at=(1,), action="corrupt"))):
        sched = DagScheduler(work_dir=str(tmp_path / "dag1"))
        got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)
    ss = xla_stats.shuffle_stats()
    assert ss["shuffle_device_fallbacks"] == 1
    fs = xla_stats.fault_stats()
    assert fs["stage_recoveries"] == 1
    assert fs["recovered_map_tasks"] == 1
    # device collect + file fallback + lineage re-run for the poisoned
    # map task; its healthy sibling skips the recovery round
    assert sched.task_runs[(0, 0)] == 3
    assert sched.task_runs[(0, 1)] == 2
