"""Aggregation tests incl. the fuzz pattern of the reference
(ref agg_exec.rs:498 test_agg, :803 fuzztest — random batches, agg vs a
host reference)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu import schema as S
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.agg import (AggExec, AggMode, CollectAgg, CountAgg,
                               make_agg)


@pytest.fixture(autouse=True)
def big_budget():
    MemManager.init(4 << 30)
    yield


def run_agg(table, group_cols, aggs, mode=AggMode.PARTIAL, batch_rows=512,
            partitions=1):
    scan = MemoryScanExec.from_arrow(table, num_partitions=partitions,
                                     batch_rows=batch_rows)
    schema = S.Schema.from_arrow(table.schema)
    group_exprs = [(col(schema.index_of(c), c), c) for c in group_cols]
    agg_list = []
    for fname, in_col, out_name in aggs:
        children = [col(schema.index_of(in_col), in_col)] if in_col else []
        agg_list.append((make_agg(fname, children), mode, out_name))
    plan = AggExec(scan, group_exprs, agg_list)
    return plan.execute_collect().to_arrow(), plan


def as_dict(tbl, key, val):
    return dict(zip(tbl.column(key).to_pylist(), tbl.column(val).to_pylist()))


def test_global_agg_sum_count_avg():
    t = pa.table({"v": pa.array([1.0, 2.0, None, 4.0])})
    got, _ = run_agg(t, [], [("sum", "v", "s"), ("count", "v", "c"),
                            ("avg", "v", "a")], AggMode.PARTIAL)
    # partial mode emits acc columns
    assert got.num_rows == 1
    got2, _ = run_agg(t, [], [("sum", "v", "s"), ("count", "v", "c"),
                              ("avg", "v", "a")], AggMode.COMPLETE)
    assert got2.column("s").to_pylist() == [7.0]
    assert got2.column("c").to_pylist() == [3]
    assert got2.column("a").to_pylist() == [pytest.approx(7.0 / 3)]


def test_grouped_sum_matches_pandas():
    rng = np.random.default_rng(0)
    n = 20000
    t = pa.table({"k": pa.array(rng.integers(0, 100, n)),
                  "v": pa.array(rng.random(n))})
    got, _ = run_agg(t, ["k"], [("sum", "v", "s")])
    want = t.to_pandas().groupby("k").v.sum()
    gd = as_dict(got, "k", "s.sum")
    assert len(gd) == 100
    for k, v in want.items():
        assert gd[k] == pytest.approx(v)


def test_grouped_string_keys_with_nulls():
    t = pa.table({
        "s": pa.array(["a", "b", None, "a", None, "b", "a"]),
        "v": pa.array([1, 2, 3, 4, 5, 6, 7]),
    })
    got, _ = run_agg(t, ["s"], [("sum", "v", "sum"), ("count", "v", "cnt")])
    gd = as_dict(got, "s", "sum.sum")
    assert gd == {"a": 12, "b": 8, None: 8}
    cd = as_dict(got, "s", "cnt.count")
    assert cd == {"a": 3, "b": 2, None: 2}


def test_min_max_first():
    t = pa.table({"k": pa.array([1, 1, 2, 2, 2]),
                  "v": pa.array([5.0, None, 3.0, 9.0, 1.0])})
    got, _ = run_agg(t, ["k"], [("min", "v", "mn"), ("max", "v", "mx"),
                               ("first", "v", "f"),
                               ("first_ignores_null", "v", "fin")])
    g = {k: i for i, k in enumerate(got.column("k").to_pylist())}
    assert got.column("mn.min").to_pylist()[g[1]] == 5.0
    assert got.column("mx.max").to_pylist()[g[2]] == 9.0
    assert got.column("f.first").to_pylist()[g[1]] == 5.0
    assert got.column("fin.first").to_pylist()[g[2]] == 3.0


def test_multi_batch_accumulation():
    # groups span many batches: partial batches must combine correctly
    n = 10000
    t = pa.table({"k": pa.array(np.arange(n) % 7),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    got, _ = run_agg(t, ["k"], [("count", "v", "c")], batch_rows=128)
    cd = as_dict(got, "k", "c.count")
    for k in range(7):
        assert cd[k] == len([x for x in range(n) if x % 7 == k])


def test_final_mode_two_phase():
    """Partial on 2 partitions -> concat -> Final merge == full agg."""
    rng = np.random.default_rng(1)
    n = 5000
    t = pa.table({"k": pa.array(rng.integers(0, 20, n)),
                  "v": pa.array(rng.random(n))})
    partial_got, plan = run_agg(t, ["k"], [("sum", "v", "s"),
                                           ("avg", "v", "a")],
                                AggMode.PARTIAL, partitions=2)
    # partial output: k, s.sum, a.sum, a.count
    scan2 = MemoryScanExec.from_arrow(partial_got)
    ps = S.Schema.from_arrow(partial_got.schema)
    final = AggExec(scan2, [(col(0, "k"), "k")], [
        (make_agg("sum", [col(1)]), AggMode.PARTIAL_MERGE, "s"),
        (make_agg("avg", [col(2), col(3)]), AggMode.FINAL, "a"),
    ])
    got = final.execute_collect().to_arrow()
    want_avg = t.to_pandas().groupby("k").v.mean()
    ga = as_dict(got, "k", "a")
    for k, v in want_avg.items():
        assert ga[k] == pytest.approx(v)


def test_collect_list_and_set():
    t = pa.table({"k": pa.array([1, 1, 2, 2, 2]),
                  "v": pa.array([3, 3, 5, 6, 5])})
    got, _ = run_agg(t, ["k"], [("collect_list", "v", "cl"),
                               ("collect_set", "v", "cs")])
    g = {k: i for i, k in enumerate(got.column("k").to_pylist())}
    assert sorted(got.column("cl.items").to_pylist()[g[1]]) == [3, 3]
    assert sorted(got.column("cs.items").to_pylist()[g[2]]) == [5, 6]


def test_agg_spill_under_pressure():
    rng = np.random.default_rng(2)
    n = 50000
    t = pa.table({"k": pa.array(rng.integers(0, 5000, n)),
                  "v": pa.array(np.ones(n, dtype=np.int64))})
    MemManager.init(150_000)
    got, plan = run_agg(t, ["k"], [("count", "v", "c"), ("sum", "v", "s")],
                        batch_rows=4096)
    assert plan.metrics.get("spill_count") >= 1
    cd = as_dict(got, "k", "c.count")
    want = t.to_pandas().groupby("k").v.count()
    assert len(cd) == len(want)
    for k, v in want.items():
        assert cd[k] == v


def test_partial_skipping_high_cardinality():
    with config.scoped(**{"auron.partialAggSkipping.minRows": 1000,
                          "auron.partialAggSkipping.ratio": 0.5}):
        n = 5000
        t = pa.table({"k": pa.array(np.arange(n)),  # all distinct
                      "v": pa.array(np.ones(n, dtype=np.int64))})
        got, plan = run_agg(t, ["k"], [("count", "v", "c")], batch_rows=512)
        assert plan.metrics.get("partial_skipped") == 1
        # pass-through partials may repeat keys across batches but counts
        # must still total n
        assert sum(got.column("c.count").to_pylist()) == n


def test_agg_fuzz_vs_pandas():
    rng = np.random.default_rng(42)
    n = 30000
    t = pa.table({
        "k1": pa.array(rng.integers(0, 50, n)),
        "k2": pa.array(np.where(rng.random(n) < 0.1, None,
                                rng.integers(0, 4, n)).tolist(),
                       type=pa.int64()),
        "v": pa.array(np.where(rng.random(n) < 0.05, np.nan, rng.random(n))),
    })
    got, _ = run_agg(t, ["k1", "k2"], [("sum", "v", "s"),
                                       ("count", "v", "c"),
                                       ("min", "v", "mn"),
                                       ("max", "v", "mx")], batch_rows=1024)
    df = t.to_pandas()
    want = df.groupby(["k1", "k2"], dropna=False).agg(
        s=("v", "sum"), c=("v", "count"),
        has_nan=("v", lambda x: np.isnan(x).any())).reset_index()
    assert got.num_rows == len(want)
    wd = {(int(r.k1), None if pd.isna(r.k2) else int(r.k2)):
          (r.s, r.c, r.has_nan) for r in want.itertuples()}
    gk = list(zip(got.column("k1").to_pylist(), got.column("k2").to_pylist()))
    gs = got.column("s.sum").to_pylist()
    gc = got.column("c.count").to_pylist()
    for k, s, c in zip(gk, gs, gc):
        ws, wc, has_nan = wd[k]
        # nulls don't count; NaN values DO count (Spark counts NaN)
        if has_nan:
            # pandas sum skips NaN; Spark (and ours) propagates it
            assert s is None or np.isnan(s)
        else:
            assert s == pytest.approx(ws)
            assert c == wc


def test_host_udaf_fallback():
    """UDAF round-trip (ref spark_udaf_wrapper.rs): geometric mean."""
    import math
    from blaze_tpu.bridge.resource import put_resource
    put_resource("udaf://geomean", (
        lambda: (0.0, 0),
        lambda st, v: st if v is None else (st[0] + math.log(v), st[1] + 1),
        lambda a, b: (a[0] + b[0], a[1] + b[1]),
        lambda st: math.exp(st[0] / st[1]) if st[1] else None,
    ))
    t = pa.table({"k": pa.array([1, 1, 2, 2]),
                  "v": pa.array([2.0, 8.0, 3.0, None])})
    scan = MemoryScanExec.from_arrow(t)
    from blaze_tpu.exprs import col
    plan = AggExec(scan, [(col(0, "k"), "k")], [
        (make_agg("udaf", [col(1)], udaf_name="geomean"),
         AggMode.COMPLETE, "gm")])
    out = plan.execute_collect().to_arrow()
    d = dict(zip(out.column("k").to_pylist(), out.column("gm").to_pylist()))
    assert d[1] == pytest.approx(4.0)
    assert d[2] == pytest.approx(3.0)


def test_high_cardinality_string_keys_under_budget(tmp_path):
    """VERDICT r2 #8: a high-cardinality string group-by under a small
    MemManager budget must spill (dictionary bytes are charged to the
    budget) and still produce exact results."""
    import pyarrow.parquet as pq

    from blaze_tpu.memory import MemManager
    from blaze_tpu.plan import create_plan

    rng = np.random.default_rng(42)
    n = 60_000
    keys = [f"customer_{i:08d}" for i in rng.integers(0, 30_000, n)]
    t = pa.table({"k": pa.array(keys),
                  "v": pa.array(rng.random(n))})
    src = str(tmp_path / "hc.parquet")
    pq.write_table(t, src)
    ir = {"kind": "hash_agg",
          "groupings": [{"expr": {"kind": "column", "name": "k"},
                         "name": "k"}],
          "aggs": [{"fn": "sum", "mode": "complete", "name": "s",
                    "args": [{"kind": "column", "name": "v"}]}],
          "input": {"kind": "parquet_scan",
                    "schema": {"fields": [
                        {"name": "k", "type": {"id": "utf8"},
                         "nullable": True},
                        {"name": "v", "type": {"id": "float64"},
                         "nullable": True}]},
                    "file_groups": [[src]]}}
    MemManager.init(512 << 10)  # 512 KiB: far below dict + partials
    try:
        plan = create_plan(ir)
        out = pa.Table.from_batches(
            [b.compact().to_arrow() for b in plan.execute(0)])
        spills = plan.collect_metrics().get("spill_count") or 0
        for ch in getattr(plan.collect_metrics(), "children", []):
            spills += ch.get("spill_count") or 0
        assert spills > 0, "expected spills under a 512KiB budget"
    finally:
        MemManager.init(4 << 30)
    got = out.to_pandas().sort_values("k").reset_index(drop=True)
    want = (t.to_pandas().groupby("k", as_index=False).v.sum()
            .sort_values("k").reset_index(drop=True))
    assert len(got) == len(want)
    np.testing.assert_allclose(got["s"].to_numpy(), want.v.to_numpy(),
                               rtol=1e-9)


def test_combine_unique_flattens_arrays():
    """brickhouse.combine_unique: union of list elements per group
    (ref agg/brickhouse/combine_unique.rs — collect_set over flattened
    input arrays)."""
    import pyarrow as pa
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import AggExec, AggMode, MemoryScanExec
    from blaze_tpu.ops.agg.functions import make_agg
    t = pa.table({"g": pa.array([1, 1, 2, 2]),
                  "a": pa.array([[1, 2], [2, 3, None], [5], None],
                                type=pa.list_(pa.int64()))})
    plan = AggExec(MemoryScanExec.from_arrow(t), [(col(0, "g"), "g")],
                   [(make_agg("combine_unique", [col(1)]),
                     AggMode.COMPLETE, "u")])
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in plan.execute(0)]).to_pandas()
    got = {int(r.g): sorted(r.u) for r in out.itertuples()}
    assert got == {1: [1, 2, 3], 2: [5]}


def test_brickhouse_collect_maps_to_collect_set():
    """ref agg/brickhouse/collect.rs delegates to AggCollectSet; enum
    1000 decodes through the wire (proto AggFunction.BRICKHOUSE_COLLECT)."""
    from blaze_tpu.exprs import col
    from blaze_tpu.ops.agg.functions import CollectAgg, make_agg
    fn = make_agg("brickhouse.collect", [col(0)])
    assert isinstance(fn, CollectAgg) and fn.name == "collect_set"
    from blaze_tpu.plan.proto_serde import _AGG_FN_DECODE, pb
    assert _AGG_FN_DECODE[pb.BRICKHOUSE_COLLECT] == "brickhouse.collect"
