"""kernels/mxu_agg: exact grouped aggregation as MXU matmuls.

The scatter reference path runs on every backend; the pallas kernel body
is additionally exercised through the interpreter so CI covers the exact
code the TPU executes (parity asserted block-for-block)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blaze_tpu.kernels import mxu_agg


def _numpy_oracle(gid, arrays, layout):
    S = layout.num_slots
    blocks = []
    keep = gid < S
    if layout.presence:
        p = np.zeros(S, np.int64)
        np.add.at(p, gid[keep], 1)
        blocks.append(p)
    for a, nl in zip(arrays, layout.limbs):
        for li in range(nl):
            w = (a.astype(np.int64) >> (8 * li)) & 255
            b = np.zeros(S, np.int64)
            np.add.at(b, gid[keep], w[keep])
            blocks.append(b)
    return blocks


def _as_blocks(table_np, layout):
    t = np.asarray(table_np).reshape(layout.sh, layout.n_blocks, layout.sl)
    return [t[:, b, :].reshape(-1).astype(np.int64)
            for b in range(layout.n_blocks)]


def _case(rows, num_slots, value_bits, seed=0, mask_frac=0.2):
    rng = np.random.default_rng(seed)
    layout = mxu_agg.plan_layout(num_slots, value_bits)
    assert layout is not None
    gid = rng.integers(0, num_slots, rows).astype(np.int32)
    # sentinel rows = filtered out
    gid[rng.random(rows) < mask_frac] = layout.num_slots
    arrays = [rng.integers(0, 1 << min(31, 8 * nl), rows).astype(np.int32)
              for nl in layout.limbs]
    return layout, gid, arrays


class TestWindowTableRef:
    @pytest.mark.parametrize("rows,slots,bits", [
        (5000, 1000, [16]),
        (20000, 16384, [8, 24]),
        (1000, 300, [32, 1]),
        (16384, 131072, [16]),
    ])
    def test_matches_numpy(self, rows, slots, bits):
        layout, gid, arrays = self._mk(rows, slots, bits)
        tab = jax.jit(
            lambda g, a: mxu_agg.window_table(g, a, layout, force_ref=True),
        )(jnp.asarray(gid), [jnp.asarray(a) for a in arrays])
        got = _as_blocks(tab, layout)
        want = _numpy_oracle(gid, arrays, layout)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def _mk(self, rows, slots, bits):
        return _case(rows, slots, bits)

    def test_split_blocks_recombines(self):
        layout, gid, arrays = _case(8000, 5000, [24, 8])
        tab = mxu_agg.window_table(jnp.asarray(gid),
                                   [jnp.asarray(a) for a in arrays],
                                   layout, force_ref=True)
        presence, vals = mxu_agg.split_blocks(np.asarray(tab), layout)
        S = layout.num_slots
        want_p = np.zeros(S, np.int64)
        keep = gid < S
        np.add.at(want_p, gid[keep], 1)
        np.testing.assert_array_equal(presence, want_p)
        for a, got in zip(arrays, vals):
            want = np.zeros(S, np.int64)
            np.add.at(want, gid[keep], a[keep].astype(np.int64))
            np.testing.assert_array_equal(got, want)

    def test_empty_and_all_masked(self):
        layout, gid, arrays = _case(512, 100, [8], mask_frac=1.0)
        tab = mxu_agg.window_table(jnp.asarray(gid),
                                   [jnp.asarray(a) for a in arrays],
                                   layout, force_ref=True)
        assert int(jnp.sum(tab)) == 0


class TestPallasInterpret:
    """The exact TPU kernel body, via the pallas interpreter."""

    @pytest.mark.parametrize("rows,slots,bits", [
        (4096, 2048, [16]),
        (40000, 16384, [8, 16]),
    ])
    def test_parity_with_ref(self, rows, slots, bits):
        layout, gid, arrays = _case(rows, slots, bits, seed=3)
        g = jnp.asarray(gid)
        a = [jnp.asarray(x) for x in arrays]
        ref = mxu_agg.window_table(g, a, layout, force_ref=True)
        got = mxu_agg.window_table(g, a, layout, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


class TestPlanLayout:
    def test_rejects_oversize(self):
        assert mxu_agg.plan_layout(1 << 20, [16]) is None   # sh > 512
        assert mxu_agg.plan_layout(1000, [40]) is None      # >4 limbs
        assert mxu_agg.plan_layout(1000, [8] * 20) is None  # too many blocks

    def test_shapes(self):
        lay = mxu_agg.plan_layout(54603, [16])
        assert lay.sl == 256 and lay.sh % 8 == 0
        assert lay.num_slots >= 54603
        assert lay.n_blocks == 1 + 2

    def test_limb_bits_for(self):
        assert mxu_agg.limb_bits_for(0, 255) == 8
        assert mxu_agg.limb_bits_for(-10, -10) == 1
        assert mxu_agg.limb_bits_for(-(10**7), 10**7) == 25
