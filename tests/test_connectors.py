"""Table-format connector tests: iceberg v2 deletes, paimon deletion
vectors, hudi COW scans, partition constants, conf gates (ref
thirdparty/auron-{iceberg,paimon,hudi}; VERDICT r1 weak #8 — these
providers previously had no tests)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import blaze_tpu.connectors  # noqa: F401  (registers providers)
from blaze_tpu import config
from blaze_tpu.connectors.provider import build_scan
from blaze_tpu.memory import MemManager
from blaze_tpu.schema import Schema


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _base_file(tmp_path, n=10_000, name="data.parquet"):
    rng = np.random.default_rng(0)
    t = pa.table({"id": pa.array(np.arange(n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    p = str(tmp_path / name)
    pq.write_table(t, p, row_group_size=2048)
    return p, t


def _collect(plan):
    out = []
    for p in range(plan.num_partitions):
        out.extend(b.compact().to_arrow() for b in plan.execute(p))
    out = [b for b in out if b.num_rows]
    return pa.Table.from_batches(out) if out else None


class TestIceberg:
    def test_positional_deletes_across_batches(self, tmp_path):
        path, t = _base_file(tmp_path)
        # delete rows scattered across row groups/batches
        deleted = [0, 5, 2047, 2048, 9000, 9999]
        dp = str(tmp_path / "del.pos.parquet")
        pq.write_table(pa.table({
            "file_path": pa.array([path] * len(deleted)),
            "pos": pa.array(deleted, type=pa.int64())}), dp)
        desc = {"splits": [{"path": path, "position_deletes": [dp]}]}
        schema = Schema.from_arrow(t.schema)
        got = _collect(build_scan("iceberg", desc, schema))
        ids = set(got["id"].to_pylist())
        assert len(ids) == t.num_rows - len(deleted)
        assert not ids.intersection(deleted)

    def test_positional_deletes_for_other_file_ignored(self, tmp_path):
        path, t = _base_file(tmp_path)
        dp = str(tmp_path / "del.pos.parquet")
        pq.write_table(pa.table({
            "file_path": pa.array(["/other/file.parquet"]),
            "pos": pa.array([0], type=pa.int64())}), dp)
        desc = {"splits": [{"path": path, "position_deletes": [dp]}]}
        got = _collect(build_scan("iceberg", desc,
                                  Schema.from_arrow(t.schema)))
        assert got.num_rows == t.num_rows

    def test_equality_deletes(self, tmp_path):
        path, t = _base_file(tmp_path, n=2000)
        ep = str(tmp_path / "del.eq.parquet")
        pq.write_table(pa.table({
            "id": pa.array([10, 20, 30], type=pa.int64())}), ep)
        desc = {"splits": [{"path": path,
                            "equality_deletes": [{"path": ep,
                                                  "equality_ids": ["id"]}]}]}
        got = _collect(build_scan("iceberg", desc,
                                  Schema.from_arrow(t.schema)))
        ids = set(got["id"].to_pylist())
        assert got.num_rows == 1997
        assert not ids.intersection({10, 20, 30})

    def test_gate_disables_provider(self, tmp_path):
        path, t = _base_file(tmp_path, n=10)
        config.conf.set("auron.enable.iceberg.scan", False)
        try:
            with pytest.raises(RuntimeError, match="disabled"):
                build_scan("iceberg", {"splits": [{"path": path}]},
                           Schema.from_arrow(t.schema))
        finally:
            config.conf.unset("auron.enable.iceberg.scan")


class TestPaimon:
    def test_deletion_vectors(self, tmp_path):
        path, t = _base_file(tmp_path, n=5000)
        desc = {"splits": [{"path": path}],
                "deletion_vectors": {path: [1, 3, 4095, 4999]}}
        got = _collect(build_scan("paimon", desc,
                                  Schema.from_arrow(t.schema)))
        ids = set(got["id"].to_pylist())
        assert got.num_rows == 4996
        assert not ids.intersection({1, 3, 4095, 4999})

    def test_partition_constants(self, tmp_path):
        path, t = _base_file(tmp_path, n=100)
        full = Schema.from_arrow(pa.schema(
            list(t.schema) + [pa.field("dt", pa.string())]))
        desc = {"splits": [{"path": path,
                            "partition_values": {"dt": "2026-07-30"}}]}
        got = _collect(build_scan("paimon", desc, full))
        assert got.num_rows == 100
        assert set(got["dt"].to_pylist()) == {"2026-07-30"}


class TestHudi:
    def test_cow_scan_multi_split(self, tmp_path):
        p1, t1 = _base_file(tmp_path, n=300, name="a.parquet")
        p2, t2 = _base_file(tmp_path, n=200, name="b.parquet")
        desc = {"splits": [{"path": p1}, {"path": p2}]}
        got = _collect(build_scan("hudi", desc,
                                  Schema.from_arrow(t1.schema),
                                  num_partitions=2))
        assert got.num_rows == 500


def test_hudi_mor_log_merge(tmp_path):
    """MOR snapshot read: log blocks upsert + delete against the base by
    record key, latest commit wins (VERDICT r3 #10 — the label now has
    an implementation behind it)."""
    import pyarrow.parquet as pq

    base = pa.table({
        "_hoodie_record_key": pa.array(["k1", "k2", "k3"]),
        "_hoodie_commit_time": pa.array(["c1", "c1", "c1"]),
        "v": pa.array([10, 20, 30], type=pa.int64())})
    log1 = pa.table({
        "_hoodie_record_key": pa.array(["k2", "k4"]),
        "_hoodie_commit_time": pa.array(["c2", "c2"]),
        "v": pa.array([21, 40], type=pa.int64())})
    log2 = pa.table({  # delete k1, re-update k2
        "_hoodie_record_key": pa.array(["k1", "k2"]),
        "_hoodie_commit_time": pa.array(["c3", "c3"]),
        "v": pa.array([0, 22], type=pa.int64()),
        "_hoodie_is_deleted": pa.array([True, False])})
    bp = str(tmp_path / "base.parquet")
    l1 = str(tmp_path / "log1.parquet")
    l2 = str(tmp_path / "log2.parquet")
    pq.write_table(base, bp)
    pq.write_table(log1, l1)
    pq.write_table(log2, l2)

    from blaze_tpu.connectors.provider import get_provider
    splits = get_provider("hudi").resolve_splits(
        {"splits": [{"path": bp, "log_files": [l1, l2]}]})
    assert len(splits) == 1 and splits[0].path != bp
    merged = pq.read_table(splits[0].path).sort_by("_hoodie_record_key")
    got = dict(zip(merged.column("_hoodie_record_key").to_pylist(),
                   merged.column("v").to_pylist()))
    assert got == {"k2": 22, "k3": 30, "k4": 40}  # k1 deleted


def test_iceberg_equality_deletes_vectorized_100k(tmp_path):
    """A 100K-row equality delete file must apply in well under a second
    (the old per-row tuple-set path took seconds; VERDICT r3 #10)."""
    import time

    import numpy as np
    import pyarrow.parquet as pq

    n = 200_000
    rng = np.random.default_rng(0)
    data = pa.table({"id": pa.array(np.arange(n)),
                     "grp": pa.array(rng.integers(0, 50, n)),
                     "v": pa.array(rng.random(n))})
    base = str(tmp_path / "data.parquet")
    pq.write_table(data, base)
    deleted_ids = np.arange(0, 2 * 100_000, 2)  # 100K deletes
    dfile = str(tmp_path / "del.eq.parquet")
    pq.write_table(pa.table({"id": pa.array(deleted_ids)}), dfile)

    desc = {"splits": [{
        "path": base,
        "equality_deletes": [{"path": dfile,
                              "equality_ids": ["id"]}]}]}
    plan = build_scan("iceberg", desc, Schema.from_arrow(data.schema))
    t0 = time.perf_counter()
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in plan.execute(0)])
    wall = time.perf_counter() - t0
    assert out.num_rows == n - len(deleted_ids)
    assert not set(out.column("id").to_pylist()) & set(deleted_ids.tolist())
    assert wall < 1.0, f"equality deletes took {wall:.2f}s"
