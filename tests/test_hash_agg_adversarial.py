"""Adversarial hash-agg table tests (VERDICT r4 weak #7).

The scatter-probe claim loop early-exits when every row places in a
round or two; these tests force the OTHER regimes:

  * load factor ~1.0 — long probe chains, probe_rounds exhaustion,
  * overflow atomicity — a failed batch must leave the carry unchanged,
  * the rehash/grow path — re-inserting a full table into a larger one
    must preserve every group and every accumulator exactly,
  * the production grow loop end-to-end against a pandas oracle.

All under jit, like the device path compiles them (ref: the reference's
agg table growth in agg/agg_table.rs is likewise exercised by its
fuzz tests).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from blaze_tpu.parallel.stage import (HashAggCarry, hash_agg_step,
                                      init_hash_carry, rehash_carry)


def _insert(carry, keys, vals, probe_rounds=16):
    n = keys.shape[0]
    step = jax.jit(lambda c, k, v, m: hash_agg_step(
        c, [(k, jnp.ones(n, bool))],
        [("sum", v, None), ("count", None, None)],
        m, probe_rounds=probe_rounds))
    return step(carry, keys, vals, jnp.ones(n, bool))


def _table_dict(carry):
    used = np.asarray(carry.used)
    keys = np.asarray(carry.keys[0])[used]
    sums = np.asarray(carry.accs[0])[used]
    counts = np.asarray(carry.accs[1])[used]
    return {int(k): (float(s), int(c))
            for k, s, c in zip(keys, sums, counts)}


def test_full_load_overflow_is_atomic():
    """64 slots, 80 distinct keys: placement MUST overflow; the returned
    carry must be bit-identical to the input (lossless retry contract)."""
    S = 64
    carry = init_hash_carry([jnp.int64], ["sum", "count"],
                            [jnp.float64, jnp.int64], S)
    keys = jnp.arange(80, dtype=jnp.int64)
    vals = jnp.ones(80, dtype=jnp.float64)
    out, overflow, _ = _insert(carry, keys, vals)
    assert int(overflow) > 0
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(carry)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_probe_rounds_exhaustion_partial_chain():
    """probe_rounds=1 with distinct keys hashing anywhere: any collision
    in round 0 overflows — and the step still reports it losslessly."""
    S = 64
    carry = init_hash_carry([jnp.int64], ["sum", "count"],
                            [jnp.float64, jnp.int64], S)
    keys = jnp.arange(60, dtype=jnp.int64)
    vals = jnp.ones(60, dtype=jnp.float64)
    out, overflow, num_groups = _insert(carry, keys, vals, probe_rounds=1)
    if int(overflow) == 0:  # statistically impossible at 60/64 in 1 round
        pytest.fail("60 keys into 64 slots placed in ONE probe round")
    # atomic: nothing was written
    assert not np.asarray(out.used).any()


def test_rehash_grow_preserves_every_group():
    """Fill a 128-slot table near capacity, grow to 512 via rehash_carry,
    keep inserting — final content must equal the pandas oracle."""
    rng = np.random.default_rng(7)
    all_keys = rng.integers(0, 200, 1024).astype(np.int64)
    all_vals = rng.random(1024)

    carry = init_hash_carry([jnp.int64], ["sum", "count"],
                            [jnp.float64, jnp.int64], 128)
    grown = False
    for lo in range(0, 1024, 256):
        k = jnp.asarray(all_keys[lo:lo + 256])
        v = jnp.asarray(all_vals[lo:lo + 256])
        out, overflow, _ = _insert(carry, k, v)
        if int(overflow) > 0:
            # production grow loop: rehash into 4x slots, retry batch
            carry, ovf2, _ = rehash_carry(carry, ["sum", "count"], 512)
            assert int(ovf2) == 0, "grow re-insert itself overflowed"
            grown = True
            out, overflow, _ = _insert(carry, k, v)
            assert int(overflow) == 0
        carry = out
    assert grown, "test never exercised the grow path (tune sizes)"

    got = _table_dict(carry)
    import pandas as pd
    want = pd.DataFrame({"k": all_keys, "v": all_vals}).groupby("k")["v"] \
        .agg(["sum", "count"])
    assert set(got) == set(want.index)
    for key, row in want.iterrows():
        s, c = got[int(key)]
        assert c == int(row["count"])
        np.testing.assert_allclose(s, row["sum"], rtol=1e-12)


def test_adversarial_same_slot_chain():
    """Keys engineered to collide: insert keys one batch at a time whose
    hashes all share low bits (found by sieving), forcing the max-length
    probe chain the early-exit skips in the common case."""
    from blaze_tpu.kernels import hashing as H
    S = 256
    # sieve int keys whose xxhash64 lands in ONE bucket of 256
    cand = np.arange(0, 400_000, dtype=np.int64)
    h = np.asarray(H.hash_columns(
        [(jnp.asarray(cand), jnp.ones(len(cand), bool), "int64")],
        seed=42, xp=jnp, algo="xxhash64")).astype(np.int64) & (S - 1)
    same = cand[h == 0][:24]  # 24 keys, one home slot: 24-long chain
    assert len(same) == 24, "sieve range too small"
    carry = init_hash_carry([jnp.int64], ["sum", "count"],
                            [jnp.float64, jnp.int64], S)
    keys = jnp.asarray(same)
    vals = jnp.ones(len(same), dtype=jnp.float64)
    out, overflow, num_groups = _insert(carry, keys, vals,
                                        probe_rounds=32)
    assert int(overflow) == 0, "32 rounds must place a 24-chain"
    assert int(num_groups) == 24
    got = _table_dict(out)
    assert set(got) == {int(k) for k in same}
    assert all(c == 1 and s == 1.0 for s, c in got.values())

    # second insert of the SAME keys must unify, not duplicate
    out2, overflow2, num_groups2 = _insert(out, keys, vals,
                                           probe_rounds=32)
    assert int(overflow2) == 0
    assert int(num_groups2) == 24
    got2 = _table_dict(out2)
    assert all(c == 2 and s == 2.0 for s, c in got2.values())
