"""Expression-layer tests: Spark null semantics, Kleene logic, casts, strings.

Modeled on the reference's per-expression unit tests
(ref: datafusion-ext-exprs/src/*.rs #[test] blocks, SURVEY.md §4 tier 1).
"""

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import schema as S
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import (BinaryExpr, CachedExprsEvaluator, CaseWhen, Cast,
                             Coalesce, If, InList, IsNotNull, IsNull, Like,
                             Not, col, lit)


def make_batch(**cols):
    arrays, fields = [], []
    for name, values in cols.items():
        arr = pa.array(values)
        fields.append(pa.field(name, arr.type))
        arrays.append(arr)
    return ColumnBatch.from_arrow(
        pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields)))


def col_py(batch, expr):
    """Evaluate expr and return python list over real rows."""
    v = expr.evaluate(batch)
    return v.to_host(batch.num_rows).to_pylist()


def test_arith_null_propagation():
    b = make_batch(a=[1, None, 3], b=[10, 20, None])
    assert col_py(b, BinaryExpr("+", col(0), col(1))) == [11, None, None]
    assert col_py(b, BinaryExpr("*", col(0), lit(2))) == [2, None, 6]


def test_division_by_zero_is_null():
    b = make_batch(a=[10, 7, 5], b=[2, 0, 0])
    assert col_py(b, BinaryExpr("/", col(0), col(1))) == [5, None, None]
    assert col_py(b, BinaryExpr("%", col(0), col(1))) == [0, None, None]


def test_int_division_truncates_toward_zero():
    b = make_batch(a=[-7, 7, -7], b=[2, -2, -2])
    assert col_py(b, BinaryExpr("/", col(0), col(1))) == [-3, -3, 3]
    # Java %: sign follows dividend
    assert col_py(b, BinaryExpr("%", col(0), col(1))) == [-1, 1, -1]


def test_pmod_matches_spark():
    b = make_batch(a=[-7, 7, -3], b=[3, 3, 5])
    # Spark: pmod(-7,3)=2, pmod(7,3)=1, pmod(-3,5)=2
    assert col_py(b, BinaryExpr("pmod", col(0), col(1))) == [2, 1, 2]


def test_kleene_and_or():
    b = make_batch(p=[True, True, False, None, None, False],
                   q=[True, None, None, None, False, False])
    assert col_py(b, BinaryExpr("and", col(0), col(1))) == \
        [True, None, False, None, False, False]
    assert col_py(b, BinaryExpr("or", col(0), col(1))) == \
        [True, True, None, None, None, False]


def test_comparison_null():
    b = make_batch(a=[1, None, 3], b=[1, 2, 2])
    assert col_py(b, BinaryExpr("==", col(0), col(1))) == [True, None, False]
    assert col_py(b, BinaryExpr("<=>", col(0), col(1))) == [True, False, False]


def test_null_safe_eq_nulls():
    b = make_batch(a=[None, None], b=[None, 1])
    assert col_py(b, BinaryExpr("<=>", col(0), col(1))) == [True, False]


def test_is_null_not():
    b = make_batch(a=[1, None, 3])
    assert col_py(b, IsNull(col(0))) == [False, True, False]
    assert col_py(b, IsNotNull(col(0))) == [True, False, True]
    bb = make_batch(p=[True, False, None])
    assert col_py(bb, Not(col(0))) == [False, True, None]


def test_case_when():
    b = make_batch(a=[1, 2, 3, None])
    e = CaseWhen(
        branches=((BinaryExpr("==", col(0), lit(1)), lit(10)),
                  (BinaryExpr("==", col(0), lit(2)), lit(20))),
        otherwise=lit(0))
    assert col_py(b, e) == [10, 20, 0, 0]
    e2 = CaseWhen(branches=((BinaryExpr("==", col(0), lit(1)), lit(10)),))
    assert col_py(b, e2) == [10, None, None, None]


def test_if_and_coalesce():
    b = make_batch(a=[1, None, 3], b=[9, 8, 7])
    assert col_py(b, If(IsNull(col(0)), col(1), col(0))) == [1, 8, 3]
    assert col_py(b, Coalesce((col(0), col(1)))) == [1, 8, 3]


def test_in_list_null_semantics():
    b = make_batch(a=[1, 2, None, 4])
    assert col_py(b, InList(col(0), (1, 2))) == [True, True, None, False]
    # null member: non-matching probes become NULL, not FALSE
    assert col_py(b, InList(col(0), (1, None))) == [True, None, None, None]


def test_cast_string_to_int_invalid_null():
    b = make_batch(s=["12", " 34 ", "x", "12.7", None])
    assert col_py(b, Cast(col(0), S.INT32)) == [12, 34, None, 12, None]


def test_cast_int_to_string():
    b = make_batch(a=[1, None, -3])
    assert col_py(b, Cast(col(0), S.UTF8)) == ["1", None, "-3"]


def test_cast_double_to_string_java_format():
    b = make_batch(a=[1.0, 2.5, float("nan")])
    assert col_py(b, Cast(col(0), S.UTF8)) == ["1.0", "2.5", "NaN"]


def test_cast_string_to_bool_and_date():
    b = make_batch(s=["true", "NO", "1", "zzz"])
    assert col_py(b, Cast(col(0), S.BOOL)) == [True, False, True, None]
    d = make_batch(s=["2023-05-17", "2023-5-1", "bad", "2023-05-17 10:00:00"])
    import datetime
    assert col_py(d, Cast(col(0), S.DATE32)) == [
        datetime.date(2023, 5, 17), datetime.date(2023, 5, 1), None,
        datetime.date(2023, 5, 17)]


def test_like_patterns():
    b = make_batch(s=["apple", "banana", "grape", None])
    assert col_py(b, Like(col(0), "%an%")) == [False, True, False, None]
    assert col_py(b, Like(col(0), "_pple")) == [True, False, False, None]
    assert col_py(b, Like(col(0), "gr%")) == [False, False, True, None]


def test_string_compare_host():
    b = make_batch(s=["a", "b", None], t=["a", "a", "a"])
    assert col_py(b, BinaryExpr("==", col(0), col(1))) == [True, False, None]
    assert col_py(b, BinaryExpr(">", col(0), col(1))) == [False, True, None]


def test_filter_evaluator_short_circuit_and_mask():
    b = make_batch(a=[1, 2, 3, 4, 5], s=["x", "y", "x", "y", "x"])
    ev = CachedExprsEvaluator(
        filters=[BinaryExpr("and",
                            BinaryExpr(">", col(0), lit(1)),
                            BinaryExpr("==", col(1), lit("x")))])
    out = ev.filter(b)
    assert out.selected_count() == 2
    packed = out.compact()
    assert packed.to_arrow().column(0).to_pylist() == [3, 5]


def test_project_with_cse():
    b = make_batch(a=[1, 2, 3])
    shared = BinaryExpr("+", col(0), lit(10))
    ev = CachedExprsEvaluator(projections=[
        shared, BinaryExpr("*", shared, lit(2))])
    out_schema = S.Schema([S.Field("x", S.INT64), S.Field("y", S.INT64)])
    out = ev.project(b, out_schema)
    assert out.to_arrow().column(0).to_pylist() == [11, 12, 13]
    assert out.to_arrow().column(1).to_pylist() == [22, 24, 26]


def test_float_mod_and_nan():
    b = make_batch(a=[7.5, float("nan"), 7.5], b=[2.0, 2.0, 0.0])
    out = col_py(b, BinaryExpr("%", col(0), col(1)))
    assert out[0] == pytest.approx(1.5)
    assert np.isnan(out[1])
    # Spark DivModLike: divisor 0 -> NULL for doubles too (non-ANSI)
    assert out[2] is None


def test_float_divide_by_zero_is_null():
    b = make_batch(a=[1.0, -2.5, 7.0], b=[0.0, 0.0, 2.0])
    out = col_py(b, BinaryExpr("/", col(0), col(1)))
    assert out == [None, None, 3.5]
