"""Speculative execution (bridge/tasks.py): quantile-driven straggler
hedging with first-wins attempt commit.  Wave-level trigger + cancel
semantics, the forced loser-commit-race fault site, the pre-dispatch
deadline fatal-classification, deterministic backoff jitter, and a
scheduler-level parity run with speculation enabled."""

import threading
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.bridge import tasks as tasks_mod
from blaze_tpu.bridge.context import TaskKilledError, current_attempt_token
from blaze_tpu.bridge.tasks import run_tasks
from blaze_tpu.faults import TaskDeadlineExpired, classify_exception
from blaze_tpu.memory import MemManager


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(1 << 30)


@pytest.fixture()
def speculation_on():
    config.conf.set(config.SPECULATION_ENABLE.key, "on")
    config.conf.set(config.SPECULATION_QUANTILE.key, 0.25)
    config.conf.set(config.SPECULATION_MULTIPLIER.key, 1.0)
    config.conf.set(config.SPECULATION_MIN_MS.key, 10)
    try:
        yield
    finally:
        for opt in (config.SPECULATION_ENABLE, config.SPECULATION_QUANTILE,
                    config.SPECULATION_MULTIPLIER, config.SPECULATION_MIN_MS):
            config.conf.unset(opt.key)


def _spec_delta(before):
    d = xla_stats.delta(before)
    return {k[len("speculation_"):]: int(v) for k, v in d.items()
            if k.startswith("speculation_")}


def test_speculation_off_is_single_attempt():
    """Default-off: one attempt per task, zero speculation counters —
    the wave loop must be byte-identical to the pre-speculation path."""
    before = xla_stats.snapshot()
    calls = []
    out = run_tasks(lambda i: calls.append(i) or i * 10, 4, 10.0,
                    "spec off wave", max_workers=4)
    assert out == [0, 10, 20, 30]
    assert sorted(calls) == [0, 1, 2, 3]          # exactly one call each
    assert all(v == 0 for v in _spec_delta(before).values())


def test_trigger_hedges_straggler_and_cancels_loser(speculation_on):
    """A straggler past multiplier x median gets a duplicate attempt;
    the duplicate's success wins, and the straggling primary is
    cooperatively cancelled through its attempt token."""
    before = xla_stats.snapshot()
    lock = threading.Lock()
    calls = {}

    def fn(i):
        with lock:
            attempt = calls[i] = calls.get(i, -1) + 1
        if i == 3 and attempt == 0:
            # primary straggles until first-wins cancels it
            tok = current_attempt_token()
            assert tok is not None
            if not tok.wait(8.0):
                raise AssertionError("straggler was never cancelled")
            raise TaskKilledError("cooperative cancel observed")
        return f"t{i}a{attempt}"

    out = run_tasks(fn, 4, 10.0, "spec trigger wave", max_workers=4)
    assert out[:3] == ["t0a0", "t1a0", "t2a0"]
    assert out[3] == "t3a1"                       # the duplicate won
    d = _spec_delta(before)
    assert d["waves"] == 1
    assert d["attempts"] >= 1
    assert d["wins"] == 1
    assert d["losers_cancelled"] >= 1
    assert d["commit_races"] == 0


def test_loser_commit_race_lets_both_attempts_finish(speculation_on):
    """The speculation-loser-commit-race site suppresses loser
    cancellation: the straggling primary runs to completion and its
    late result is discarded — first-wins already settled."""
    before = xla_stats.snapshot()
    release = threading.Event()
    finished = {}
    lock = threading.Lock()
    calls = {}

    def fn(i):
        with lock:
            attempt = calls[i] = calls.get(i, -1) + 1
        if i == 2 and attempt == 0:
            tok = current_attempt_token()
            # the loser must NOT be cancelled: the race site suppresses
            # the winner's settle_losers, so this wait times out on
            # `release`, never on the attempt token
            assert release.wait(8.0)
            assert tok is not None and not tok.is_set()
            with lock:
                finished["loser"] = True
            return "t2-loser"
        return f"t{i}a{attempt}"

    with faults.scoped(("speculation-loser-commit-race", dict(p=1.0)),
                       seed=7):
        out = run_tasks(fn, 4, 10.0, "spec race wave", max_workers=4)
    release.set()                                  # loser may now finish
    assert out[2] == "t2a1"                        # winner, not the loser
    deadline = time.monotonic() + 5.0
    while "loser" not in finished and time.monotonic() < deadline:
        time.sleep(0.01)
    assert finished.get("loser")                   # loser ran to the end
    d = _spec_delta(before)
    assert d["commit_races"] >= 1
    assert d["losers_cancelled"] == 0


def test_pre_dispatch_deadline_is_fatal():
    """TaskDeadlineExpired must classify fatal — a task whose deadline
    expired before dispatch must not burn maxAttempts backoff sleeps —
    while a plain TimeoutError stays retryable (OSError subclass)."""
    assert classify_exception(
        TaskDeadlineExpired("worker task deadline already expired")) \
        == "fatal"
    assert classify_exception(TimeoutError("socket timed out")) \
        == "retryable"

    calls = []

    def fn(i):
        calls.append(i)
        raise TaskDeadlineExpired("worker task deadline already expired")

    with pytest.raises(TaskDeadlineExpired):
        run_tasks(fn, 1, 10.0, "expired wave", max_workers=1)
    assert calls == [0]                            # no retry burned


def test_backoff_jitter_deterministic():
    """Jitter derives from (faults seed, what, task, attempt) so chaos
    soaks replay identically; different coordinates decorrelate."""
    j = tasks_mod._backoff_jitter
    assert j("stage 1", 3, 2) == j("stage 1", 3, 2)
    assert 0.0 <= j("stage 1", 3, 2) < 1.0
    coords = {("stage 1", 3, 2), ("stage 1", 3, 3), ("stage 1", 4, 2),
              ("stage 2", 3, 2)}
    vals = {j(w, t, a) for (w, t, a) in coords}
    assert len(vals) == len(coords)                # no collisions here


def test_scheduler_parity_with_speculation_on(tmp_path, speculation_on):
    """A staged two-stage aggregate with speculation enabled (attempt-
    suffixed shuffle files + promote/resolve arbitration on the file
    tier) returns the same frame as the single-attempt path, and the
    scheduler's leak report stays clean."""
    from blaze_tpu.plan.stages import DagScheduler
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        rng = np.random.default_rng(11)
        n = 20_000
        t = pa.table({"k": pa.array(rng.integers(0, 200, n),
                                    type=pa.int64()),
                      "v": pa.array(rng.random(n))})
        paths = []
        for i in range(2):
            p = str(tmp_path / f"in-{i}.parquet")
            pq.write_table(t.slice(i * (n // 2), n // 2), p)
            paths.append(p)
        schema = {"fields": [
            {"name": "k", "type": {"id": "int64"}, "nullable": True},
            {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
        plan = {
            "kind": "hash_agg",
            "groupings": [{"expr": {"kind": "column", "index": 0},
                           "name": "k"}],
            "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                      "args": [{"kind": "column", "index": 1}]}],
            "input": {
                "kind": "local_exchange",
                "partitioning": {"kind": "hash",
                                 "exprs": [{"kind": "column", "index": 0}],
                                 "num_partitions": 3},
                "input": {
                    "kind": "hash_agg",
                    "groupings": [{"expr": {"kind": "column", "name": "k"},
                                   "name": "k"}],
                    "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                              "args": [{"kind": "column", "name": "v"}]}],
                    "input": {"kind": "parquet_scan", "schema": schema,
                              "file_groups": [[paths[0]], [paths[1]]]}}}}
        sched = DagScheduler(work_dir=str(tmp_path / "dag"))
        got = sched.run_collect(plan).to_pandas()
        want = t.to_pandas().groupby("k", as_index=False).v.sum() \
            .rename(columns={"v": "s"})
        got = got.sort_values("k").reset_index(drop=True)
        want = want.sort_values("k").reset_index(drop=True)
        assert len(got) == len(want)
        np.testing.assert_allclose(got["s"].to_numpy(),
                                   want["s"].to_numpy(), rtol=1e-9)
        leaks = sched.leak_report()
        assert sum(len(v) for v in leaks.values()) == 0, leaks
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


def test_explain_analyze_reports_speculation(speculation_on):
    """explain_analyze output grows a speculation: footer once hedging
    happened in the profiled run."""
    from blaze_tpu.plan.explain import format_speculation_footer
    stats = {"speculation_waves": 2, "speculation_attempts": 3,
             "speculation_wins": 2, "speculation_losers_cancelled": 3,
             "speculation_loser_commits_rejected": 1,
             "speculation_commit_races": 0,
             "speculation_duplicate_commits": 0}
    line = format_speculation_footer(stats)
    assert line is not None
    assert "speculation:" in line
    assert "waves=2" in line and "wins=2" in line
    assert format_speculation_footer(
        {k: 0 for k in stats}) is None             # quiet when unused
