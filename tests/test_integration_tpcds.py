"""TPC-DS integration tests (the dev/auron-it tier, SURVEY.md §4 tier 4).

Every query runs the full production path: synthetic tables written to
parquet file splits -> JSON-IR plan dict -> create_plan -> fuse_plan ->
execute, compared cell-wise against a pandas oracle, with plan-stability
goldens snapshotted from the DECODED (and fused) plan.

Scale: BLAZE_TPCDS_SCALE env (default 0.2; BASELINE configs call for 1.0 —
run `BLAZE_TPCDS_SCALE=1.0 pytest tests/test_integration_tpcds.py` for
the full SF1 tier).
"""

import os

import pytest

pytestmark = pytest.mark.slow  # deselect with -m 'not slow'

from blaze_tpu.itest import check_plan_stability, generate, run_query
from blaze_tpu.itest.queries import QUERIES
from blaze_tpu.itest.tpcds_data import write_parquet_splits
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan
from blaze_tpu.plan.fused import fuse_plan

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")
SCALE = float(os.environ.get("BLAZE_TPCDS_SCALE", "0.2"))


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _build(qname, tmp_path, scale=SCALE, partitions=2):
    builder, table_names = QUERIES[qname]
    tables = generate(table_names, scale=scale)
    paths = write_parquet_splits(tables, str(tmp_path), partitions)
    plan_dict, oracle = builder(paths, tables, partitions)
    return plan_dict, oracle


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpcds_query(qname, tmp_path):
    plan_dict, oracle = _build(qname, tmp_path)
    plan = fuse_plan(create_plan(plan_dict))
    res = run_query(qname, plan, oracle)
    assert res.passed, f"{qname}: {res.detail}"
    diff = check_plan_stability(
        plan, os.path.join(GOLDEN_DIR, f"{qname}.plan.txt"),
        update=os.environ.get("BLAZE_UPDATE_GOLDENS") == "1")
    assert diff is None, f"plan changed for {qname}:\n{diff}"


def _spill_counts(metrics) -> int:
    total = metrics.get("spill_count") or 0
    for child in getattr(metrics, "children", []):
        total += _spill_counts(child)
    return int(total)


def test_q01_spills_under_pressure(tmp_path):
    """End-to-end spill: a tiny memory budget must drive the shuffle /
    agg consumers to disk without changing the result (VERDICT r1 #4).
    The plan runs un-fused (create_plan only, no fuse_plan), so the eager
    MemConsumer aggregation path carries the load."""
    plan_dict, oracle = _build("q01", tmp_path, scale=0.2)
    MemManager.init(256 << 10)  # 256 KiB budget
    try:
        plan = create_plan(plan_dict)
        res = run_query("q01-spill", plan, oracle)
        assert res.passed, res.detail
        spills = _spill_counts(plan.collect_metrics())
        assert spills > 0, \
            "expected at least one spill under a 256KiB budget"
    finally:
        MemManager.init(4 << 30)
