"""TPC-DS integration tests (the dev/auron-it tier, SURVEY.md §4 tier 4).

Every query runs the full production path: synthetic tables written to
parquet file splits -> JSON-IR plan dict -> create_plan -> fuse_plan ->
execute, compared cell-wise against a pandas oracle, with plan-stability
goldens snapshotted from the DECODED (and fused) plan.

Scale: BLAZE_TPCDS_SCALE env (default 0.2; BASELINE configs call for 1.0 —
run `BLAZE_TPCDS_SCALE=1.0 pytest tests/test_integration_tpcds.py` for
the full SF1 tier).
"""

import os

import pytest

pytestmark = pytest.mark.slow  # deselect with -m 'not slow'

from blaze_tpu.itest import check_plan_stability, generate, run_query
from blaze_tpu.itest.queries import QUERIES
from blaze_tpu.itest.tpcds_data import write_parquet_splits
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan
from blaze_tpu.plan.fused import fuse_plan

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")
SCALE = float(os.environ.get("BLAZE_TPCDS_SCALE", "0.2"))


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def _build(qname, tmp_path, scale=SCALE, partitions=2):
    builder, table_names = QUERIES[qname]
    tables = generate(table_names, scale=scale)
    paths = write_parquet_splits(tables, str(tmp_path), partitions)
    plan_dict, oracle = builder(paths, tables, partitions)
    return plan_dict, oracle


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpcds_query(qname, tmp_path):
    plan_dict, oracle = _build(qname, tmp_path)
    plan = fuse_plan(create_plan(plan_dict))
    res = run_query(qname, plan, oracle)
    assert res.passed, f"{qname}: {res.detail}"
    diff = check_plan_stability(
        plan, os.path.join(GOLDEN_DIR, f"{qname}.plan.txt"),
        update=os.environ.get("BLAZE_UPDATE_GOLDENS") == "1")
    assert diff is None, f"plan changed for {qname}:\n{diff}"


def _spill_counts(metrics) -> int:
    total = metrics.get("spill_count") or 0
    for child in getattr(metrics, "children", []):
        total += _spill_counts(child)
    return int(total)


def test_q01_spills_under_pressure(tmp_path):
    """End-to-end spill: a tiny memory budget must drive the shuffle /
    agg consumers to disk without changing the result (VERDICT r1 #4).
    The plan runs un-fused (create_plan only, no fuse_plan), so the eager
    MemConsumer aggregation path carries the load."""
    plan_dict, oracle = _build("q01", tmp_path, scale=0.2)
    MemManager.init(256 << 10)  # 256 KiB budget
    try:
        plan = create_plan(plan_dict)
        res = run_query("q01-spill", plan, oracle)
        assert res.passed, res.detail
        spills = _spill_counts(plan.collect_metrics())
        assert spills > 0, \
            "expected at least one spill under a 256KiB budget"
    finally:
        MemManager.init(4 << 30)


@pytest.mark.slow
def test_wire_query_on_real_accelerator():
    """Device-placement wire path on REAL accelerator hardware: q52
    through DagScheduler with auron.tpu.placement=device.  Skips on
    CPU-only environments (the itest/CI tier pins jax to cpu); run
    without JAX_PLATFORMS to exercise the actual chip (see
    DEVICE_WIRE_r04.json for a recorded run)."""
    import jax

    from blaze_tpu import config
    if jax.default_backend() == "cpu":
        pytest.skip("no accelerator backend in this environment")
    import tempfile

    import pandas as pd

    from blaze_tpu.bridge import placement as P
    from blaze_tpu.itest import generate
    from blaze_tpu.itest.queries import QUERIES
    from blaze_tpu.itest.runner import compare_frames
    from blaze_tpu.itest.tpcds_data import write_parquet_splits
    from blaze_tpu.plan.stages import DagScheduler
    config.conf.set(config.PLACEMENT.key, "device")
    P._info = None  # re-decide placement under the forced policy
    try:
        builder, tn = QUERIES["q52"]
        tables = generate(tn, scale=0.05)
        with tempfile.TemporaryDirectory() as tmp:
            paths = write_parquet_splits(tables, tmp, 2)
            plan_dict, oracle = builder(paths, tables, 2)
            got = DagScheduler(work_dir=tmp + "/dag").run_collect(
                plan_dict)
            g = got.to_pandas() if got.num_rows else pd.DataFrame(
                {n: [] for n in got.schema.names})
            assert compare_frames(g, oracle()) is None
    finally:
        config.conf.unset(config.PLACEMENT.key)
        P._info = None
