"""TPC-DS integration tests: queries vs pandas oracle + plan stability
(the dev/auron-it tier, SURVEY.md §4 tier 4)."""

import os

import pytest

from blaze_tpu.itest import (check_plan_stability, generate, run_query)
from blaze_tpu.itest.queries import QUERIES
from blaze_tpu.memory import MemManager

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_tpcds_query(qname):
    builder, table_names = QUERIES[qname]
    tables = generate(table_names, scale=0.02)
    plan, oracle = builder(tables)
    res = run_query(qname, plan, oracle)
    assert res.passed, f"{qname}: {res.detail}"
    # plan stability vs golden (created on first run, then enforced)
    diff = check_plan_stability(
        plan, os.path.join(GOLDEN_DIR, f"{qname}.plan.txt"),
        update=os.environ.get("BLAZE_UPDATE_GOLDENS") == "1")
    assert diff is None, f"plan changed for {qname}:\n{diff}"
