"""Test config: force CPU platform with 8 virtual devices so sharding /
collective paths are exercised without TPU hardware (the reference's analog:
spark-local[N] exercising the full shuffle path without a cluster,
SURVEY.md §4)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
