"""Test config: force CPU platform with 8 virtual devices so sharding /
collective paths are exercised without TPU hardware (the reference's analog:
spark-local[N] exercising the full shuffle path without a cluster,
SURVEY.md §4).

Note: in this environment the axon TPU plugin ignores the JAX_PLATFORMS env
var, so the override must go through jax.config before first backend use.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


import pytest  # noqa: E402


@pytest.fixture(scope="session")
def device_mesh():
    """Session-wide dp mesh over every virtual device (8 on the forced
    host platform above); multi-device collective tests share it so the
    shard_map programs compile once per session."""
    from blaze_tpu.parallel.mesh import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("multi-device mesh unavailable")
    return make_mesh(len(jax.devices()))


def _build_native_libs() -> None:
    """Build the C++ libs (zstd IPC codec + host bridge) so their tests
    are always load-bearing instead of skipped (VERDICT r3 #9).  Cached:
    rebuilds only when a source/CMake file is newer than the libs."""
    import glob
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(root, "native")
    build = os.path.join(native, "build")
    libs = (glob.glob(os.path.join(build, "**", "libblaze_*.so"),
                      recursive=True) if os.path.isdir(build) else [])
    srcs = (glob.glob(os.path.join(native, "src", "*")) +
            [os.path.join(native, "CMakeLists.txt")])
    try:
        if libs and srcs:
            newest_src = max(os.path.getmtime(p) for p in srcs)
            oldest_lib = min(os.path.getmtime(p) for p in libs)
            if oldest_lib >= newest_src:
                return
        subprocess.run(["cmake", "-S", native, "-B", build, "-G", "Ninja"],
                       check=True, capture_output=True, timeout=300)
        subprocess.run(["cmake", "--build", build], check=True,
                       capture_output=True, timeout=600)
    except Exception as e:  # missing toolchain/files: tests fall to skips
        import warnings
        warnings.warn(f"native lib build failed ({e}); "
                      f"bridge/codec tests will skip")


_build_native_libs()
