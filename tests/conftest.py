"""Test config: force CPU platform with 8 virtual devices so sharding /
collective paths are exercised without TPU hardware (the reference's analog:
spark-local[N] exercising the full shuffle path without a cluster,
SURVEY.md §4).

Note: in this environment the axon TPU plugin ignores the JAX_PLATFORMS env
var, so the override must go through jax.config before first backend use.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
