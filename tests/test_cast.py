"""Spark cast-matrix tests mirroring the reference vectors
(datafusion-ext-commons/src/arrow/cast.rs:540-1000)."""

import decimal as pydec

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import Cast, TryCast, col
from blaze_tpu.schema import (BOOL, DataType, FLOAT64, INT32, INT64, UTF8,
                              TypeId, decimal)

I32MAX, I32MIN = 2**31 - 1, -(2**31)


def _cast_values(values, src_type: pa.DataType, to: DataType,
                 expr_cls=Cast):
    t = pa.table({"c": pa.array(values, type=src_type)})
    cb = ColumnBatch.from_arrow(t.to_batches()[0])
    out = expr_cls(col(0), to).evaluate(cb).to_host(cb.num_rows)
    return out.to_pylist()


class TestReferenceVectors:
    def test_boolean_to_string(self):
        # ref cast.rs:541
        got = _cast_values([None, True, False], pa.bool_(), UTF8)
        assert got == [None, "true", "false"]

    def test_float_to_int(self):
        # ref cast.rs:553 — truncate, saturate at int bounds, NaN -> 0
        vals = [None, 123.456, 987.654, I32MAX + 10000.0, I32MIN - 10000.0,
                float("inf"), float("-inf"), float("nan")]
        got = _cast_values(vals, pa.float64(), INT32)
        assert got == [None, 123, 987, I32MAX, I32MIN, I32MAX, I32MIN, 0]

    def test_int_to_float(self):
        # ref cast.rs:582
        got = _cast_values([None, 123, 987, I32MAX, I32MIN], pa.int32(),
                           FLOAT64)
        assert got == [None, 123.0, 987.0, float(I32MAX), float(I32MIN)]

    def test_int_to_decimal_38_18(self):
        # ref cast.rs:605
        got = _cast_values([None, 123, 987, I32MAX, I32MIN], pa.int32(),
                           decimal(38, 18))
        want_unscaled = [None, 123 * 10**18, 987 * 10**18,
                         I32MAX * 10**18, I32MIN * 10**18]
        got_unscaled = [None if v is None else int(v.scaleb(18))
                        for v in got]
        assert got_unscaled == want_unscaled

    def test_string_to_decimal_38_18(self):
        # ref cast.rs:629 — scientific notation, padding, rounding
        vals = [None, "1e-8", "1.012345678911111111e10", "1.42e-6",
                "0.00000142", "123.456", "987.654",
                "123456789012345.678901234567890",
                "-123456789012345.678901234567890"]
        got = _cast_values(vals, pa.utf8(), decimal(38, 18))
        want = [None, 10000000000, 10123456789111111110000000000,
                1420000000000, 1420000000000, 123456000000000000000,
                987654000000000000000,
                123456789012345678901234567890000,
                -123456789012345678901234567890000]
        with pydec.localcontext() as ctx:
            ctx.prec = 76  # unscaling a decimal128 needs > the default 28
            got_unscaled = [None if v is None else int(v.scaleb(18))
                            for v in got]
        assert got_unscaled == want

    def test_decimal_to_string(self):
        # ref cast.rs:661 — full scale with trailing zeros
        unscaled = [None, 123 * 10**18, 987 * 10**18, 987654321 * 10**12,
                    I32MAX * 10**18, I32MIN * 10**18]
        vals = [None if u is None else pydec.Decimal(u).scaleb(-18)
                for u in unscaled]
        got = _cast_values(vals, pa.decimal128(38, 18), UTF8)
        assert got == [None, "123.000000000000000000",
                       "987.000000000000000000", "987.654321000000000000",
                       "2147483647.000000000000000000",
                       "-2147483648.000000000000000000"]

    def test_string_to_bigint(self):
        # ref cast.rs:692 — trim, fractional truncation, overflow -> null
        vals = [None, "123", "987", "987.654", "123456789012345",
                "-123456789012345", "999999999999999999999999999999999"]
        got = _cast_values(vals, pa.utf8(), INT64)
        assert got == [None, 123, 987, 987, 123456789012345,
                       -123456789012345, None]

    def test_string_to_date(self):
        # ref cast.rs:722 — partial dates fill with 01; invalid -> null
        vals = [None, "2001-02-03", "2001-03-04", "2001-04-05T06:07:08",
                "2001-04", "2002", "2001-00", "2001-13", "9999-99",
                "99999-01"]
        got = _cast_values(vals, pa.utf8(), DataType(TypeId.DATE32))
        strs = [None if d is None else d.isoformat() for d in got]
        assert strs == [None, "2001-02-03", "2001-03-04", "2001-04-05",
                        "2001-04-01", "2002-01-01", None, None, None, None]

    def test_struct_to_string(self):
        # ref cast.rs:755 — "{1, a, true}", nulls print as "null"
        st = pa.struct([("i", pa.int32()), ("s", pa.utf8()),
                        ("b", pa.bool_())])
        vals = [{"i": 1, "s": "a", "b": True},
                {"i": 2, "s": None, "b": False},
                {"i": None, "s": "c", "b": True},
                {"i": 4, "s": "d", "b": None},
                {"i": None, "s": None, "b": None}]
        got = _cast_values(vals, st, UTF8)
        assert got == ["{1, a, true}", "{2, null, false}",
                       "{null, c, true}", "{4, d, null}",
                       "{null, null, null}"]

    def test_map_to_string(self):
        # ref cast.rs:872 — "{1 -> a, 2 -> b}"
        mt = pa.map_(pa.int32(), pa.utf8())
        vals = [[(1, "a"), (2, "b")], [(3, None)], None]
        got = _cast_values(vals, mt, UTF8)
        assert got == ["{1 -> a, 2 -> b}", "{3 -> null}", None]


class TestDecimalRescale:
    def test_widen_and_narrow_scale(self):
        vals = [pydec.Decimal("1.23"), pydec.Decimal("-0.5"), None]
        got = _cast_values(vals, pa.decimal128(10, 2), decimal(12, 4))
        assert [None if v is None else str(v) for v in got] == \
            ["1.2300", "-0.5000", None]
        # HALF_UP when narrowing
        vals = [pydec.Decimal("1.2350"), pydec.Decimal("-1.2350")]
        got = _cast_values(vals, pa.decimal128(10, 4), decimal(10, 2))
        assert [str(v) for v in got] == ["1.24", "-1.24"]

    def test_overflow_to_null(self):
        vals = [pydec.Decimal("99999.99"), pydec.Decimal("1.00")]
        got = _cast_values(vals, pa.decimal128(7, 2), decimal(4, 2))
        assert got[0] is None and str(got[1]) == "1.00"


class TestAnsiMode:
    def _with_ansi(self, fn):
        config.conf.set(config.ANSI_ENABLED.key, True)
        try:
            return fn()
        finally:
            config.conf.unset(config.ANSI_ENABLED.key)

    def test_cast_raises_on_malformed_string(self):
        with pytest.raises(ValueError, match="CAST_INVALID_INPUT"):
            self._with_ansi(
                lambda: _cast_values(["12", "abc"], pa.utf8(), INT64))

    def test_try_cast_still_nulls(self):
        got = self._with_ansi(
            lambda: _cast_values(["12", "abc"], pa.utf8(), INT64,
                                 expr_cls=TryCast))
        assert got == [12, None]

    def test_cast_raises_on_decimal_overflow(self):
        with pytest.raises(ValueError, match="CAST_INVALID_INPUT"):
            self._with_ansi(lambda: _cast_values(
                [pydec.Decimal("99999.99")], pa.decimal128(7, 2),
                decimal(4, 2)))

    def test_valid_input_passes_under_ansi(self):
        got = self._with_ansi(
            lambda: _cast_values(["12", "34"], pa.utf8(), INT64))
        assert got == [12, 34]

    def test_null_input_is_not_an_ansi_error(self):
        got = self._with_ansi(
            lambda: _cast_values([None, "7"], pa.utf8(), INT64))
        assert got == [None, 7]


class TestReviewRegressions:
    def test_infinity_string_to_int_is_null(self):
        got = _cast_values(["Infinity", "-Inf", "NaN", "5"], pa.utf8(),
                           INT32, expr_cls=TryCast)
        assert got == [None, None, None, 5]

    def test_trim_string_disabled_nulls_padded_numerics(self):
        config.conf.set(config.CAST_TRIM_STRING.key, False)
        try:
            got = _cast_values([" 12", "12"], pa.utf8(), INT64,
                               expr_cls=TryCast)
            assert got == [None, 12]
            got = _cast_values([" 1.5", "1.5"], pa.utf8(), decimal(20, 2),
                               expr_cls=TryCast)
            assert got[0] is None and str(got[1]) == "1.50"
        finally:
            config.conf.unset(config.CAST_TRIM_STRING.key)


class TestReferenceCastVectors:
    """Bit-for-bit vectors from the reference's cast test module
    (ref datafusion-ext-commons/src/arrow/cast.rs:532-754)."""

    def _cast(self, arr, to):
        from blaze_tpu.batch import ColumnBatch
        from blaze_tpu.exprs import col
        from blaze_tpu.exprs.cast import Cast
        from blaze_tpu.schema import Schema
        t = pa.table({"c": arr})
        cb = ColumnBatch.from_arrow(t)
        v = Cast(col(0), to).evaluate(cb)
        return v.to_host(cb.num_rows)

    def test_float_to_int(self):
        # ref cast.rs:553 test_float_to_int: truncate, saturate, NaN -> 0
        import blaze_tpu.schema as S
        f = pa.array([None, 123.456, 987.654, 2**31 - 1 + 10000.0,
                      -(2**31) - 10000.0, float("inf"), float("-inf"),
                      float("nan")], type=pa.float64())
        got = self._cast(f, S.INT32).to_pylist()
        assert got == [None, 123, 987, 2**31 - 1, -(2**31),
                       2**31 - 1, -(2**31), 0]

    def test_string_to_bigint(self):
        # ref cast.rs:692 test_string_to_bigint: truncation at '.',
        # overflow -> null; plus the scientific-notation rejection the
        # to_integer port mandates
        import blaze_tpu.schema as S
        arr = pa.array([None, "123", "987", "987.654",
                        "123456789012345", "-123456789012345",
                        "999999999999999999999999999999999",
                        "1e3", "12.a", "+7", "-", "", "a1"])
        got = self._cast(arr, S.INT64).to_pylist()
        assert got == [None, 123, 987, 987, 123456789012345,
                       -123456789012345, None, None, None, 7, None,
                       None, None]

    def test_string_to_date(self):
        # ref cast.rs:722 test_string_to_date (Spark stringToDate rules)
        import blaze_tpu.schema as S
        arr = pa.array([None, "2001-02-03", "2001-03-04",
                        "2001-04-05T06:07:08", "2001-04", "2002",
                        "2001-00", "2001-13", "9999-99", "99999-01",
                        "01", "2001-04extra"])
        got = [None if v is None else str(v) for v in
               self._cast(arr, S.DATE32).to_pylist()]
        assert got == [None, "2001-02-03", "2001-03-04", "2001-04-05",
                       "2001-04-01", "2002-01-01", None, None, None,
                       None, None, None]

    def test_int_to_decimal_and_back(self):
        # ref cast.rs:605/661: int -> decimal(p,s), decimal -> plain string
        import blaze_tpu.schema as S
        dec = S.DataType(S.TypeId.DECIMAL, 10, 2)
        arr = pa.array([None, 1, 23, 456], type=pa.int64())
        d = self._cast(arr, dec)
        assert [None if v is None else str(v) for v in d.to_pylist()] == \
            [None, "1.00", "23.00", "456.00"]
        s = self._cast(d, S.UTF8)
        assert s.to_pylist() == [None, "1.00", "23.00", "456.00"]

    def test_string_to_decimal_scientific(self):
        # ref cast.rs:629 + to_plain_string: e-notation parses exactly
        import blaze_tpu.schema as S
        dec = S.DataType(S.TypeId.DECIMAL, 12, 3)
        arr = pa.array(["1.5e2", "-2E1", "0.001", "bogus", None])
        got = [None if v is None else str(v) for v in
               self._cast(arr, dec).to_pylist()]
        assert got == ["150.000", "-20.000", "0.001", None, None]

    def test_boolean_to_string(self):
        # ref cast.rs:541
        import blaze_tpu.schema as S
        arr = pa.array([None, True, False])
        assert self._cast(arr, S.UTF8).to_pylist() == [None, "true",
                                                       "false"]


class TestNestedToString:
    """Reference vectors: cast.rs test_nested_struct_to_string /
    test_struct_to_string_with_null_struct / test_nested_map_to_string."""

    def _cast_utf8(self, arr):
        import pyarrow as pa
        from blaze_tpu.batch import ColumnBatch
        from blaze_tpu.exprs.base import BoundReference
        from blaze_tpu.exprs.cast import Cast
        from blaze_tpu.schema import DataType, TypeId
        t = pa.table({"x": arr})
        cb = ColumnBatch.from_arrow(t.combine_chunks())
        e = Cast(BoundReference(0, "x"), DataType(TypeId.UTF8))
        return e.evaluate(cb).to_host(cb.num_rows).to_pylist()

    def test_nested_struct_to_string(self):
        import pyarrow as pa
        outer = pa.array(
            [{"i": {"a": 1, "b": "x"}, "c": 5},
             {"i": None, "c": 6}],
            type=pa.struct([("i", pa.struct([("a", pa.int64()),
                                             ("b", pa.string())])),
                            ("c", pa.int64())]))
        assert self._cast_utf8(outer) == ["{{1, x}, 5}", "{null, 6}"]

    def test_null_struct_row_stays_null(self):
        import pyarrow as pa
        arr = pa.array([{"a": 1}, None],
                       type=pa.struct([("a", pa.int64())]))
        assert self._cast_utf8(arr) == ["{1}", None]

    def test_map_to_string_spark_format(self):
        import pyarrow as pa
        m = pa.array([[("k1", 1), ("k2", 2)], None],
                     type=pa.map_(pa.string(), pa.int64()))
        assert self._cast_utf8(m) == ["{k1 -> 1, k2 -> 2}", None]
