"""Steady-state compilation guard (ISSUE 3 acceptance): a repeated
filter->project query must run warm with ZERO XLA recompiles and an expr
program cache hit rate >= 0.9 — per-partition evaluator instances and
repeated runs must all resolve to the one fingerprint-keyed program.

ISSUE 8 extends the guard to StageProgram: the device-resident stage
loop must build ONE program per (chain, reduce-kinds, dtype, grow)
fingerprint, hit the cache on every later run, and keep steady state at
zero recompiles even while the capacity ladder regrows the hash table
mid-partition."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu.bridge import xla_stats
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.exprs.program import clear_program_cache
from blaze_tpu.ops import FilterProjectExec, MemoryScanExec


@pytest.fixture(autouse=True)
def _fresh():
    clear_program_cache()
    yield
    clear_program_cache()


def _plan(tbl, partitions=1):
    scan = MemoryScanExec.from_arrow(tbl, num_partitions=partitions,
                                     batch_rows=256)
    return FilterProjectExec(
        scan,
        [BinaryExpr(">", col(0), lit(0)),
         BinaryExpr("<", col(1), lit(40.0))],
        [col(0), BinaryExpr("*", col(1), lit(2.0)),
         BinaryExpr("+", col(0), col(0))],
        ["a", "b2", "a2"])


def _table(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({"a": pa.array(rng.integers(-50, 50, n)),
                     "b": pa.array(rng.random(n) * 100)})


def test_steady_state_zero_recompiles():
    tbl = _table()
    _plan(tbl).execute_collect()  # warm-up: builds + compiles the program
    before = xla_stats.snapshot()
    for run in range(10):
        out = _plan(tbl).execute_collect()
        assert out.num_rows > 0
    d = xla_stats.delta(before)
    assert d["total_compiles"] == 0, \
        f"steady-state recompiles: {d['total_compiles']}"
    assert d["expr_programs_built"] == 0
    # every steady-state run is a cache hit: 10/10
    looked_up = d["expr_programs_built"] + d["expr_program_cache_hits"]
    hit_rate = d["expr_program_cache_hits"] / looked_up if looked_up else 0.0
    assert hit_rate >= 0.9, f"expr cache hit rate {hit_rate:.2f} < 0.9"
    # and every batch dispatched through the fused program, none eagerly
    assert d["expr_fused_batches"] > 0
    assert d["expr_eager_batches"] == 0


def test_partitions_share_one_program():
    # satellite: per-partition evaluator instances must meter under ONE
    # kernel name — no false per-partition recompiles
    tbl = _table(4096, seed=1)
    plan = _plan(tbl, partitions=4)
    before = xla_stats.snapshot()
    plan.execute_collect()
    d = xla_stats.delta(before)
    assert d["expr_programs_built"] == 1
    assert d["expr_program_cache_hits"] >= 3  # partitions 2..4
    assert d["total_compiles"] <= 1, \
        f"per-partition recompiles detected: {d['total_compiles']}"


def test_cross_query_program_reuse():
    # two distinct scans, same expression chain + dtypes: the second
    # query reuses the first's compiled program without any compile
    _plan(_table(seed=2)).execute_collect()
    before = xla_stats.snapshot()
    _plan(_table(seed=3)).execute_collect()
    d = xla_stats.delta(before)
    assert d["expr_programs_built"] == 0
    assert d["total_compiles"] == 0


# -- ISSUE 8: StageProgram guard (device-resident stage loop) ---------------

@pytest.fixture
def loop_on():
    from blaze_tpu.plan import stage_compiler
    stage_compiler._SEEN_FINGERPRINTS.clear()
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")
    try:
        yield
    finally:
        config.conf.unset(config.STAGE_DEVICE_LOOP_ENABLE.key)


def _loop_agg_plan(tmp_path, tag="a", n=4000, mode="partial",
                   value="float64", seed=5):
    """hash_agg over a 2-partition parquet scan.  Keys are WIDE int64
    (compact 0..199 ranges take the dense lane, which the stage compiler
    rejects — the loop is the hash lane's fold)."""
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 200, n) * 1000003 + 17
    if value == "int64":
        v = pa.array(rng.integers(0, 1000, n), type=pa.int64())
    else:
        v = pa.array(rng.random(n))
    t = pa.table({"k": pa.array(k, type=pa.int64()), "v": v})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"loop-{tag}-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": value}, "nullable": True}]}
    return {"kind": "hash_agg",
            "groupings": [{"expr": {"kind": "column", "index": 0},
                           "name": "k"}],
            "aggs": [{"fn": "sum", "mode": mode, "name": "s",
                      "args": [{"kind": "column", "index": 1}]}],
            "input": {"kind": "parquet_scan", "schema": schema,
                      "file_groups": [[paths[0]], [paths[1]]]}}


def _fused(plan_dict):
    from blaze_tpu.plan.column_pruning import prune_columns
    from blaze_tpu.plan.fused import fuse_plan
    from blaze_tpu.plan.planner import collapse_filter_project, create_plan
    return fuse_plan(prune_columns(collapse_filter_project(
        create_plan(plan_dict))))


def test_stage_loop_steady_state_zero_recompiles(tmp_path, loop_on):
    plan = _fused(_loop_agg_plan(tmp_path))
    nparts = plan.num_partitions
    for p in range(nparts):  # warm-up: builds the program, compiles fold
        assert list(plan.execute(p))
    before = xla_stats.snapshot()
    runs = 0
    for _ in range(3):
        fresh = _fused(_loop_agg_plan(tmp_path))  # new plan instances
        for p in range(nparts):
            assert list(fresh.execute(p))
            runs += 1
    d = xla_stats.delta(before)
    assert d["total_compiles"] == 0, \
        f"steady-state recompiles: {d['total_compiles']}"
    assert d["stage_loop_programs_built"] == 0
    assert d["stage_loop_program_cache_hits"] >= runs
    assert d["stage_loop_fallbacks"] == 0
    # and the loop actually ran every partition (not the staged path)
    assert d["stage_loop_tasks"] == runs


def test_stage_loop_new_dtype_signature_builds_new_program(tmp_path,
                                                           loop_on):
    plan = _fused(_loop_agg_plan(tmp_path, tag="f"))
    assert list(plan.execute(0))
    before = xla_stats.snapshot()
    other = _fused(_loop_agg_plan(tmp_path, tag="i", value="int64"))
    assert list(other.execute(0))
    d = xla_stats.delta(before)
    # int64 accumulator => new dtype signature => exactly one new program
    assert d["stage_loop_programs_built"] == 1
    assert d["stage_loop_fallbacks"] == 0


# -- ISSUE 9: Pallas kernel lane guard --------------------------------------

@pytest.fixture
def pallas_on():
    config.conf.set(config.KERNELS_PALLAS.key, "on")
    try:
        yield
    finally:
        config.conf.unset(config.KERNELS_PALLAS.key)


@pytest.mark.pallas
def test_pallas_lane_capacity_rungs_compile_once(tmp_path, loop_on,
                                                 pallas_on):
    # the rung ladder with the kernel lane forced on: the warm run
    # compiles one placement kernel per capacity rung (the lane rides
    # the fold/rehash cache keys); the repeat run climbs the same
    # ladder with ZERO new compiles and zero fallbacks
    config.conf.set(config.ON_DEVICE_AGG_CAPACITY.key, 16)
    try:
        plan = _fused(_loop_agg_plan(tmp_path, tag="prung", mode="final"))
        assert list(plan.execute(0))
        before = xla_stats.snapshot()
        again = _fused(_loop_agg_plan(tmp_path, tag="prung",
                                      mode="final"))
        assert list(again.execute(0))
        d = xla_stats.delta(before)
        assert d["total_compiles"] == 0, \
            f"pallas-lane rung recompiles: {d['total_compiles']}"
        assert d["stage_loop_regrows"] > 0
        assert d["stage_loop_fallbacks"] == 0
        # the kernel lane actually resolved (interpret on a CPU session)
        assert (d["scatter_lane_hash_interpret"]
                + d["scatter_lane_hash_pallas"]) > 0
    finally:
        config.conf.unset(config.ON_DEVICE_AGG_CAPACITY.key)


def test_stage_loop_capacity_rungs_compile_once(tmp_path, loop_on):
    # exact (final) mode grows the table on overflow: capacity 16 with
    # ~200 groups forces the rung ladder.  The warm run compiles every
    # rung's rehash + the one fold program; the repeat run climbs the
    # same ladder with ZERO new compiles.
    config.conf.set(config.ON_DEVICE_AGG_CAPACITY.key, 16)
    try:
        plan = _fused(_loop_agg_plan(tmp_path, tag="rung", mode="final"))
        assert list(plan.execute(0))
        before = xla_stats.snapshot()
        again = _fused(_loop_agg_plan(tmp_path, tag="rung", mode="final"))
        assert list(again.execute(0))
        d = xla_stats.delta(before)
        assert d["total_compiles"] == 0, \
            f"capacity-rung recompiles: {d['total_compiles']}"
        assert d["stage_loop_regrows"] > 0  # the ladder actually climbed
        assert d["stage_loop_fallbacks"] == 0
    finally:
        config.conf.unset(config.ON_DEVICE_AGG_CAPACITY.key)
