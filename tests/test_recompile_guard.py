"""Steady-state compilation guard (ISSUE 3 acceptance): a repeated
filter->project query must run warm with ZERO XLA recompiles and an expr
program cache hit rate >= 0.9 — per-partition evaluator instances and
repeated runs must all resolve to the one fingerprint-keyed program."""

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.bridge import xla_stats
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.exprs.program import clear_program_cache
from blaze_tpu.ops import FilterProjectExec, MemoryScanExec


@pytest.fixture(autouse=True)
def _fresh():
    clear_program_cache()
    yield
    clear_program_cache()


def _plan(tbl, partitions=1):
    scan = MemoryScanExec.from_arrow(tbl, num_partitions=partitions,
                                     batch_rows=256)
    return FilterProjectExec(
        scan,
        [BinaryExpr(">", col(0), lit(0)),
         BinaryExpr("<", col(1), lit(40.0))],
        [col(0), BinaryExpr("*", col(1), lit(2.0)),
         BinaryExpr("+", col(0), col(0))],
        ["a", "b2", "a2"])


def _table(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({"a": pa.array(rng.integers(-50, 50, n)),
                     "b": pa.array(rng.random(n) * 100)})


def test_steady_state_zero_recompiles():
    tbl = _table()
    _plan(tbl).execute_collect()  # warm-up: builds + compiles the program
    before = xla_stats.snapshot()
    for run in range(10):
        out = _plan(tbl).execute_collect()
        assert out.num_rows > 0
    d = xla_stats.delta(before)
    assert d["total_compiles"] == 0, \
        f"steady-state recompiles: {d['total_compiles']}"
    assert d["expr_programs_built"] == 0
    # every steady-state run is a cache hit: 10/10
    looked_up = d["expr_programs_built"] + d["expr_program_cache_hits"]
    hit_rate = d["expr_program_cache_hits"] / looked_up if looked_up else 0.0
    assert hit_rate >= 0.9, f"expr cache hit rate {hit_rate:.2f} < 0.9"
    # and every batch dispatched through the fused program, none eagerly
    assert d["expr_fused_batches"] > 0
    assert d["expr_eager_batches"] == 0


def test_partitions_share_one_program():
    # satellite: per-partition evaluator instances must meter under ONE
    # kernel name — no false per-partition recompiles
    tbl = _table(4096, seed=1)
    plan = _plan(tbl, partitions=4)
    before = xla_stats.snapshot()
    plan.execute_collect()
    d = xla_stats.delta(before)
    assert d["expr_programs_built"] == 1
    assert d["expr_program_cache_hits"] >= 3  # partitions 2..4
    assert d["total_compiles"] <= 1, \
        f"per-partition recompiles detected: {d['total_compiles']}"


def test_cross_query_program_reuse():
    # two distinct scans, same expression chain + dtypes: the second
    # query reuses the first's compiled program without any compile
    _plan(_table(seed=2)).execute_collect()
    before = xla_stats.snapshot()
    _plan(_table(seed=3)).execute_collect()
    d = xla_stats.delta(before)
    assert d["expr_programs_built"] == 0
    assert d["total_compiles"] == 0
