"""Admission-controlled query service (serving/): admission + shedding,
deadline/cancel cooperative teardown, per-query memory quotas with the
degradation ladder, cross-query arbitration, interruptible backoff,
concurrent-safe cleanup, and the elastic RSS shuffle tier."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import query_scope
from blaze_tpu.bridge.resource import get_resource, put_resource
from blaze_tpu.bridge.tasks import run_tasks
from blaze_tpu.exprs import col
from blaze_tpu.memory import MemManager
from blaze_tpu.memory.manager import MemConsumer
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.agg import AggExec, AggMode, make_agg
from blaze_tpu.ops.base import effective_batch_size
from blaze_tpu.plan.stages import DagScheduler
from blaze_tpu.serving import (DeadlineExceeded, QueryCancelled,
                               QueryContext, QueryMemoryExceeded,
                               QueryRejected, QueryService)


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    try:
        yield
    finally:
        faults.clear()
        MemManager.init(4 << 30)


@pytest.fixture
def fast_retries():
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 1)
    try:
        yield
    finally:
        config.conf.unset(config.TASK_RETRY_BACKOFF_MS.key)


@pytest.fixture
def staged_path():
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


def _two_stage_plan(tmp_path, n=20_000, n_reduce=3, seed=7, tag="",
                    n_keys=200):
    rng = np.random.default_rng(seed)
    t = pa.table({"k": pa.array(rng.integers(0, n_keys, n),
                                type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"in{tag}-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}


def _sorted_df(tbl):
    return tbl.to_pandas().sort_values("k").reset_index(drop=True)


# -- QueryContext ------------------------------------------------------------

def test_cancel_first_wins_and_check_raises():
    ctx = QueryContext("qx", tenant="t")
    assert not ctx.cancelled
    ctx.check()  # live: no-op
    assert ctx.cancel("stop it") is True
    assert ctx.cancel("too late", kind="deadline") is False  # first wins
    with pytest.raises(QueryCancelled, match="stop it"):
        ctx.check()
    assert ctx.wait_cancelled(0.0) is True


def test_deadline_autocancels_on_check():
    ctx = QueryContext(deadline_ms=1)
    time.sleep(0.01)
    with pytest.raises(DeadlineExceeded):
        ctx.check()
    assert ctx.cancelled


def test_degrade_ladder_rungs_then_kill():
    ctx = QueryContext(mem_quota=123)
    assert ctx.degrade() == "agg-passthrough"
    assert ctx.force_agg_passthrough and ctx.capacity_shrink == 0
    assert ctx.degrade() == "shrink-capacity"
    assert ctx.capacity_shrink == 1
    assert not ctx.cancelled
    assert ctx.degrade() == "kill"
    with pytest.raises(QueryMemoryExceeded, match="123"):
        ctx.check()


def test_effective_batch_size_shrinks_with_ladder():
    assert effective_batch_size(8192) == 8192
    ctx = QueryContext()
    ctx.degrade()          # rung 1: no shrink yet
    ctx.degrade()          # rung 2: halve once
    with query_scope(ctx):
        assert effective_batch_size(8192) == 4096
        assert effective_batch_size(300) == 256  # floor


# -- admission & load shedding ----------------------------------------------

def _blocking_executor(release: threading.Event):
    def ex(plan, ctx, handle):
        while not release.wait(0.01):
            ctx.check()
        return "done"
    return ex


def test_queue_full_sheds_typed():
    release = threading.Event()
    svc = QueryService(max_concurrent=1, max_queue=1,
                       executor=_blocking_executor(release))
    try:
        running = svc.submit({"kind": "noop"})
        time.sleep(0.05)  # let it start (leaves the queue)
        queued = svc.submit({"kind": "noop"})
        with pytest.raises(QueryRejected) as e:
            svc.submit({"kind": "noop"})
        assert e.value.kind == "queue-full"
        assert svc.stats()["counters"]["shed_queue_full"] == 1
        release.set()
        assert running.result(10) == "done"
        assert queued.result(10) == "done"
    finally:
        release.set()
        svc.shutdown()


def test_tenant_quota_sheds_only_that_tenant():
    release = threading.Event()
    svc = QueryService(max_concurrent=1, max_queue=16,
                       tenant_max_inflight=2,
                       executor=_blocking_executor(release))
    try:
        hs = [svc.submit({"kind": "noop"}, tenant="hog") for _ in range(2)]
        with pytest.raises(QueryRejected) as e:
            svc.submit({"kind": "noop"}, tenant="hog")
        assert e.value.kind == "tenant-quota"
        # another tenant still admits
        other = svc.submit({"kind": "noop"}, tenant="polite")
        release.set()
        for h in hs + [other]:
            assert h.result(10) == "done"
    finally:
        release.set()
        svc.shutdown()


def test_memory_admission_sheds_on_estimate(tmp_path):
    plan = _two_stage_plan(tmp_path, n=4_000)
    svc = QueryService(admit_mem_bytes=16,  # any real file beats 16B
                       executor=lambda p, c, h: "ran")
    try:
        with pytest.raises(QueryRejected) as e:
            svc.submit(plan)
        assert e.value.kind == "memory"
        # un-stat-able input (no file scans) always admits
        assert svc.submit({"kind": "memory_scan"}).result(10) == "ran"
    finally:
        svc.shutdown()


def test_injected_admit_fault_sheds():
    svc = QueryService(executor=lambda p, c, h: "ran")
    try:
        with faults.scoped(("admit", dict(p=1.0))):
            with pytest.raises(QueryRejected) as e:
                svc.submit({"kind": "noop"})
        assert e.value.kind == "injected"
        assert svc.stats()["counters"]["shed_injected"] == 1
        assert svc.submit({"kind": "noop"}).result(10) == "ran"
    finally:
        svc.shutdown()


def test_shutdown_rejects_new_queries():
    svc = QueryService(executor=lambda p, c, h: "ran")
    svc.shutdown()
    with pytest.raises(QueryRejected) as e:
        svc.submit({"kind": "noop"})
    assert e.value.kind == "shutdown"


# -- cancellation & deadlines ------------------------------------------------

def test_cancel_queued_query_sheds_at_pop():
    release = threading.Event()
    ran = []

    def ex(plan, ctx, handle):
        ran.append(ctx.query_id)
        while not release.wait(0.01):
            ctx.check()
        return "done"

    svc = QueryService(max_concurrent=1, max_queue=4, executor=ex)
    try:
        running = svc.submit({"kind": "noop"})
        time.sleep(0.05)
        queued = svc.submit({"kind": "noop"})
        assert queued.cancel() is True
        release.set()
        assert running.result(10) == "done"
        with pytest.raises(QueryCancelled):
            queued.result(10)
        assert queued.status == "cancelled"
        assert queued.query_id not in ran  # zero work done
    finally:
        release.set()
        svc.shutdown()


def test_cancel_running_query_tears_down_within_a_step():
    steps = []

    def ex(plan, ctx, handle):
        for i in range(1000):
            ctx.check()   # the per-batch cooperative point
            steps.append(i)
            time.sleep(0.005)
        return "done"

    svc = QueryService(max_concurrent=1, executor=ex)
    try:
        h = svc.submit({"kind": "noop"})
        time.sleep(0.05)
        assert svc.cancel(h.query_id) is True
        with pytest.raises(QueryCancelled):
            h.result(10)
        n_at_cancel = len(steps)
        time.sleep(0.05)
        assert len(steps) <= n_at_cancel + 1  # stopped within one step
        assert svc.stats()["counters"]["cancelled"] == 1
    finally:
        svc.shutdown()


def test_deadline_on_staged_query_tears_down_clean(tmp_path, staged_path):
    plan = _two_stage_plan(tmp_path, n=8_000)
    svc = QueryService(max_concurrent=2)
    try:
        h = svc.submit(plan, deadline_ms=1)
        with pytest.raises(DeadlineExceeded):
            h.result(60)
        assert h.status == "cancelled"
        assert svc.stats()["counters"]["deadline"] == 1
        # full teardown: no shuffle files, resources or scratch dirs left
        assert h.leak_report is not None
        assert all(v == [] for v in h.leak_report.values()), h.leak_report
    finally:
        svc.shutdown()


def test_retry_backoff_interruptible_by_cancel():
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 30_000)
    config.conf.set(config.TASK_MAX_ATTEMPTS.key, 4)
    try:
        ctx = QueryContext("qb")

        def always_fails(i):
            raise IOError("transient")  # classified retryable

        timer = threading.Timer(0.15, ctx.cancel, args=("bored",))
        timer.start()
        t0 = time.monotonic()
        with pytest.raises(QueryCancelled):
            run_tasks(always_fails, 1, timeout_s=90, what="backoff-test",
                      query=ctx)
        elapsed = time.monotonic() - t0
        timer.cancel()
        # without the interruptible sleep this sits out a 30s backoff
        assert elapsed < 5, f"backoff not interrupted ({elapsed:.1f}s)"
    finally:
        config.conf.unset(config.TASK_RETRY_BACKOFF_MS.key)
        config.conf.unset(config.TASK_MAX_ATTEMPTS.key)


# -- cleanup & leak checks ---------------------------------------------------

def test_cleanup_concurrent_and_idempotent(tmp_path):
    (tmp_path / "dag").mkdir()
    sched = DagScheduler(work_dir=str(tmp_path / "dag"))
    files = []
    for i in range(16):
        p = str(tmp_path / "dag" / f"s-{i}.data")
        with open(p, "wb") as f:
            f.write(b"x" * 64)
        files.append(p)
    sched._files.extend(files)
    for i in range(4):
        rid = f"stage://test/{i}"
        put_resource(rid, lambda r: iter(()))
        sched._resources.append(rid)

    errors = []

    def call():
        try:
            sched.cleanup()
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    threads = [threading.Thread(target=call) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert not any(os.path.exists(p) for p in files)
    assert all(get_resource(f"stage://test/{i}") is None for i in range(4))
    report = sched.leak_report()
    assert all(v == [] for v in report.values()), report
    sched.cleanup()  # still safe afterwards


def test_failed_query_removes_shuffle_files(tmp_path, staged_path,
                                            fast_retries):
    plan = _two_stage_plan(tmp_path, n=4_000)
    config.conf.set(config.TASK_MAX_ATTEMPTS.key, 2)
    try:
        sched = DagScheduler()
        with faults.scoped(("task-start", dict(p=1.0))):
            with pytest.raises(faults.InjectedFault):
                sched.run_collect(plan)
        report = sched.leak_report()
        assert all(v == [] for v in report.values()), report
    finally:
        config.conf.unset(config.TASK_MAX_ATTEMPTS.key)


# -- per-query quotas & cross-query arbitration ------------------------------

class _FakeConsumer(MemConsumer):
    def __init__(self, name, query=None, releasable=0):
        super().__init__(name)
        self.query = query
        self.releasable = releasable
        self.spill_calls = 0
        self.release_calls = 0

    def spill(self):
        self.spill_calls += 1
        released = self._mem_used
        self._mem_used = 0
        return released

    def try_release_pressure(self):
        self.release_calls += 1
        if self.releasable:
            released = min(self.releasable, self._mem_used)
            self._mem_used -= released
            return released
        return 0


def test_quota_breach_walks_degradation_ladder():
    mgr = MemManager(total_bytes=1 << 30)  # global pool never pressures
    ctx = QueryContext("qq", mem_quota=1000)
    c = _FakeConsumer("agg", query=ctx)
    c.set_spillable(mgr)
    c.update_mem_used(500)      # under quota: nothing happens
    assert ctx.degrade_level == 0
    c.update_mem_used(2000)     # breach 1: pass-through rung + spill
    assert ctx.degrade_level == 1 and ctx.force_agg_passthrough
    assert c.spill_calls == 1   # shed its own state largest-first
    c.update_mem_used(2000)     # breach 2: shrink rung
    assert ctx.degrade_level == 2 and ctx.capacity_shrink == 1
    c.update_mem_used(2000)     # breach 3: kill
    assert ctx.cancelled
    with pytest.raises(QueryMemoryExceeded):
        ctx.check()
    assert mgr.total_quota_breaches == 3
    c.unregister()


def test_injected_quota_breach_forces_ladder():
    mgr = MemManager(total_bytes=1 << 30)
    ctx = QueryContext("qf", mem_quota=0)  # no quota set
    c = _FakeConsumer("agg", query=ctx)
    c.set_spillable(mgr)
    with faults.scoped(("quota-breach", dict(at=(1,)))):
        c.update_mem_used(10)
        assert ctx.degrade_level == 1   # fault forced the first rung
        c.update_mem_used(20)
        assert ctx.degrade_level == 1   # only the scripted occurrence
    c.unregister()


def test_arbitration_order_heaviest_query_first():
    mgr = MemManager(total_bytes=1 << 30)
    heavy, light = QueryContext("heavy"), QueryContext("light")
    h1 = _FakeConsumer("h1", query=heavy)
    h2 = _FakeConsumer("h2", query=heavy)
    l1 = _FakeConsumer("l1", query=light)
    solo = _FakeConsumer("solo")
    for c in (h1, h2, l1, solo):
        c.set_spillable(mgr)
    h1._mem_used, h2._mem_used = 300, 500       # heavy total 800
    l1._mem_used = 600                          # light total 600
    solo._mem_used = 100
    order = [c.name for c in mgr._arbitration_order()]
    # heavy query pays first, ITS largest consumer leading; the light
    # query's single bigger-than-h2 consumer still waits its turn
    assert order == ["h2", "h1", "l1", "solo"]
    for c in (h1, h2, l1, solo):
        c.unregister()


def test_global_pressure_spills_heavy_spares_light():
    mgr = MemManager(total_bytes=1000)
    heavy, light = QueryContext("heavy"), QueryContext("light")
    h = _FakeConsumer("h", query=heavy)
    li = _FakeConsumer("l", query=light)
    h.set_spillable(mgr)
    li.set_spillable(mgr)
    li._mem_used = 200
    h.update_mem_used(900)  # pool at 1100 > 1000: arbitrate
    assert h.spill_calls == 1       # heavy paid
    assert li.spill_calls == 0      # light untouched
    assert mgr.mem_used <= 800      # back under total * MEM_SPILL_FACTOR
    assert mgr.first_shed_query == "heavy"
    assert mgr.shed_bytes_by_query == {"heavy": 900}

    # now the LIGHT query's thread observes the pressure: the hog is
    # only FLAGGED (a foreign thread must never mutate its state) and
    # sheds itself at its own next update; light is never the payer
    h._mem_used = 900
    li.update_mem_used(200)
    assert h.spill_calls == 1 and h._release_requested
    assert li.spill_calls == 0
    h.update_mem_used(900)  # honors the pending release request
    assert h.spill_calls == 2 and not h._release_requested
    assert mgr.shed_bytes_by_query == {"heavy": 1800}
    h.unregister()
    li.unregister()


def test_cross_query_arbitration_bit_identical(tmp_path, staged_path):
    """Satellite: two queries over a small budget — the heavy one
    spills/degrades, the light one completes untouched, and both match
    their solo runs bit-for-bit."""
    # heavy = high-cardinality groups (real retained agg state);
    # light = a handful of groups (near-zero state)
    heavy_plan = _two_stage_plan(tmp_path, n=60_000, n_keys=60_000,
                                 tag="h", seed=7)
    light_plan = _two_stage_plan(tmp_path, n=2_000, n_keys=20,
                                 tag="l", seed=11)
    solo_heavy = _sorted_df(DagScheduler().run_collect(heavy_plan))
    solo_light = _sorted_df(DagScheduler().run_collect(light_plan))

    MemManager.init(256 << 10)  # 256 KiB shared pool: heavy must shed
    scheds = {}

    def ex(plan, ctx, handle):
        sched = DagScheduler(query_ctx=ctx)
        try:
            return sched.run_collect(plan)
        finally:
            scheds[ctx.query_id] = sched

    svc = QueryService(max_concurrent=2, executor=ex)
    try:
        hh = svc.submit(heavy_plan, query_id="heavy")
        hl = svc.submit(light_plan, query_id="light")
        got_heavy = _sorted_df(hh.result(120))
        got_light = _sorted_df(hl.result(120))
    finally:
        svc.shutdown()
    assert got_heavy.equals(solo_heavy)
    assert got_light.equals(solo_light)

    def shed_evidence(qid):
        total = {"spilled_bytes": 0, "partial_skipped": 0}

        def fold(node):
            for k in total:
                total[k] += int(node.values.get(k, 0) or 0)
            for c in node.children:
                fold(c)

        for tree in scheds[qid].stage_metrics.values():
            fold(tree)
        return total

    mm = MemManager.get()
    shed = dict(mm.shed_bytes_by_query)
    # arbitration fired, and the hog paid FIRST and paid materially
    assert mm.total_spill_count + mm.total_pressure_releases > 0
    assert mm.first_shed_query == "heavy", (mm.first_shed_query, shed)
    assert shed.get("heavy", 0) > 0, shed
    # the light query was never degraded, and at most pocket change of
    # its state was ever touched (arbitration reaches another query's
    # consumers only after the hog's releases fell short)
    assert hl.ctx.degrade_level == 0
    assert shed.get("light", 0) <= max(4096, shed["heavy"] // 10), shed
    assert sum(shed_evidence("light").values()) <= 4096


# -- forced partial-agg pass-through (degradation rung 1) --------------------

def test_degraded_query_forces_agg_passthrough():
    n = 6000
    t = pa.table({"k": pa.array(np.arange(n) % 5),   # LOW cardinality:
                  "v": pa.array(np.ones(n, dtype=np.int64))})

    def run(ctx):
        scan = MemoryScanExec.from_arrow(t, batch_rows=512)
        plan = AggExec(scan, [(col(0, "k"), "k")],
                       [(make_agg("count", [col(1, "v")]),
                         AggMode.PARTIAL, "c")])
        with query_scope(ctx):
            return plan.execute_collect().to_arrow(), plan

    _, plain = run(None)
    assert plain.metrics.get("partial_skipped") == 0  # probe says hash

    ctx = QueryContext("qd")
    ctx.degrade()  # rung 1
    got, degraded = run(ctx)
    assert degraded.metrics.get("partial_skipped") == 1  # forced
    # pass-through stays correct: every row represented exactly once
    counts = got.column(got.num_columns - 1).to_pylist()
    assert sum(counts) == n


# -- elastic shuffle tier (rss) ----------------------------------------------

def test_rss_tier_bit_identical_and_clean(tmp_path, staged_path):
    plan = _two_stage_plan(tmp_path, n=8_000)
    solo = _sorted_df(DagScheduler().run_collect(plan))
    root = tmp_path / "rss-root"
    root.mkdir()
    config.conf.set(config.SHUFFLE_SERVICE.key, str(root))
    try:
        sched = DagScheduler()
        got = _sorted_df(sched.run_collect(plan))
        assert got.equals(solo)
        report = sched.leak_report()
        assert all(v == [] for v in report.values()), report
        assert os.listdir(str(root)) == []  # rss shuffle dirs removed
    finally:
        config.conf.unset(config.SHUFFLE_SERVICE.key)


def test_rss_retry_pushes_fresh_attempt(tmp_path, staged_path,
                                        fast_retries):
    plan = _two_stage_plan(tmp_path, n=8_000)
    solo = _sorted_df(DagScheduler().run_collect(plan))
    root = tmp_path / "rss-root"
    root.mkdir()
    config.conf.set(config.SHUFFLE_SERVICE.key, str(root))
    try:
        # task-start fault on the first attempt: the retry must commit
        # under a fresh attempt id and readers accept exactly one
        with faults.scoped(("task-start", dict(at=(1,)))):
            sched = DagScheduler()
            got = _sorted_df(sched.run_collect(plan))
        assert got.equals(solo)
    finally:
        config.conf.unset(config.SHUFFLE_SERVICE.key)


def test_local_files_when_service_unset(tmp_path, staged_path):
    plan = _two_stage_plan(tmp_path, n=4_000)
    sched = DagScheduler()
    sched.run_collect(plan)
    assert sched._rss_clients == []  # fallback tier: local files only


# -- http surface ------------------------------------------------------------

def test_http_serving_stats_and_cancel():
    from blaze_tpu.bridge.profiling import (start_http_service,
                                            stop_http_service)
    release = threading.Event()
    svc = QueryService(max_concurrent=1,
                       executor=_blocking_executor(release))
    port = start_http_service(0)
    try:
        h = svc.submit({"kind": "noop"}, tenant="http")
        time.sleep(0.05)
        base = f"http://127.0.0.1:{port}"
        stats = json.loads(urllib.request.urlopen(
            f"{base}/serving", timeout=10).read())
        assert any(s["running"] == 1 for s in stats["services"])
        out = json.loads(urllib.request.urlopen(
            f"{base}/serving/cancel?qid={h.query_id}", timeout=10).read())
        assert out == {"query_id": h.query_id, "cancelled": True}
        with pytest.raises(QueryCancelled):
            h.result(10)
    finally:
        release.set()
        svc.shutdown()
        stop_http_service()
