"""L6 converter tests: Spark TreeNode-JSON plans -> engine IR ->
create_plan -> execution vs pandas (ref AuronConverters.scala:189 dispatch,
NativeConverters.scala:329 expressions, AuronConvertStrategy gates)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu.convert import ConversionError, convert_spark_plan
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan

CAT = "org.apache.spark.sql.catalyst.expressions."
EXEC = "org.apache.spark.sql.execution."


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


# -- TreeNode-JSON authoring helpers (flat pre-order arrays) ----------------

def attr(name, dt, eid):
    return [{"class": CAT + "AttributeReference", "num-children": 0,
             "name": name, "dataType": dt, "nullable": True,
             "exprId": {"id": eid, "jvmId": "u"}}]


def lit(value, dt):
    return [{"class": CAT + "Literal", "num-children": 0,
             "value": value, "dataType": dt}]


def binexpr(cls, l, r):
    return [{"class": CAT + cls, "num-children": 2}] + l + r


def alias(child, name, eid):
    return [{"class": CAT + "Alias", "num-children": 1, "name": name,
             "exprId": {"id": eid, "jvmId": "u"}}] + child


def sort_order(child, desc=False):
    return [{"class": CAT + "SortOrder", "num-children": 1,
             "direction": ("Descending" if desc else "Ascending"),
             "nullOrdering": ("NullsLast" if desc else "NullsFirst")}] + \
        child


def agg_expr(fn_cls, arg, mode, result_id):
    return [{"class": CAT + "aggregate.AggregateExpression",
             "num-children": 1, "mode": mode, "isDistinct": False,
             "resultId": {"id": result_id, "jvmId": "u"}},
            {"class": CAT + f"aggregate.{fn_cls}",
             "num-children": len([arg]) if arg else 0}] + (arg or [])


def scan_node(attrs, files):
    return [{"class": EXEC + "FileSourceScanExec",
             "num-children": 0,
             "output": [a for a in attrs],
             "files": files}]


def plan_node(cls, fields, children):
    out = [{"class": EXEC + cls, "num-children": len(children), **fields}]
    for c in children:
        out += c
    return out


def _write(tmp_path, t, name="t.parquet"):
    p = str(tmp_path / name)
    pq.write_table(t, p)
    return [[p]]


def _run(ir):
    plan = create_plan(ir)
    out = []
    for p in range(plan.num_partitions):
        out.extend(b.compact().to_arrow() for b in plan.execute(p))
    out = [b for b in out if b.num_rows]
    return (pa.Table.from_batches(out).to_pandas() if out
            else pd.DataFrame())


def test_scan_filter_project_binds_by_expr_id(tmp_path):
    # two columns with the SAME NAME, distinct exprIds: name-based binding
    # would silently pick the wrong one (the Catalyst shadowing case)
    t = pa.table({"x": pa.array([1, 2, 3, 4], type=pa.int64()),
                  "x_": pa.array([10, 20, 30, 40], type=pa.int64())})
    t = t.rename_columns(["x", "x"])
    files = _write(tmp_path, t)
    a1, a2 = attr("x", "long", 1), attr("x", "long", 2)
    plan = plan_node(
        "ProjectExec",
        {"projectList": [alias(binexpr("Add", attr("x", "long", 2),
                                       lit("5", "long")), "y", 3)]},
        [plan_node("FilterExec",
                   {"condition": binexpr(">", [], [])[:0] or
                    binexpr("GreaterThan", attr("x", "long", 1),
                            lit("1", "long"))},
                   [scan_node([a1[0], a2[0]], files)])])
    res = convert_spark_plan(plan)
    # binding must resolve exprId 2 -> column index 1 (the second "x")
    got = _run(res.plan)
    assert got["y"].tolist() == [25, 35, 45]
    assert res.output_names == ["y"]


def test_two_stage_aggregate_with_exchange(tmp_path):
    rng = np.random.default_rng(0)
    n = 5000
    t = pa.table({"k": pa.array(rng.integers(0, 40, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    files = _write(tmp_path, t)
    k, v = attr("k", "long", 1), attr("v", "double", 2)
    partial = plan_node(
        "aggregate.HashAggregateExec",
        {"groupingExpressions": [attr("k", "long", 1)],
         "aggregateExpressions": [agg_expr("Sum", attr("v", "double", 2),
                                           "Partial", 10)]},
        [scan_node([k[0], v[0]], files)])
    exchange = plan_node(
        "exchange.ShuffleExchangeExec",
        {"outputPartitioning": [
            {"class": CAT + "HashPartitioning", "num-children": 1,
             "numPartitions": 2},
            attr("k", "long", 1)[0]]},
        [partial])
    final = plan_node(
        "aggregate.HashAggregateExec",
        {"groupingExpressions": [attr("k", "long", 1)],
         "aggregateExpressions": [agg_expr("Sum", None, "Final", 10)]},
        [exchange])
    res = convert_spark_plan(final)
    got = _run(res.plan).sort_values("k").reset_index(drop=True)
    want = t.to_pandas().groupby("k", as_index=False).v.sum() \
        .sort_values("k").reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_allclose(got.iloc[:, 1].to_numpy(),
                               want.v.to_numpy(), rtol=1e-9)


def test_broadcast_hash_join(tmp_path):
    rng = np.random.default_rng(1)
    big = pa.table({"k": pa.array(rng.integers(0, 50, 3000),
                                  type=pa.int64()),
                    "v": pa.array(rng.random(3000))})
    dim = pa.table({"dk": pa.array(np.arange(0, 50, 2), type=pa.int64()),
                    "name": pa.array([f"d{i}" for i in range(0, 50, 2)])})
    f_big = _write(tmp_path, big, "big.parquet")
    f_dim = _write(tmp_path, dim, "dim.parquet")
    k, v = attr("k", "long", 1), attr("v", "double", 2)
    dk, nm = attr("dk", "long", 3), attr("name", "string", 4)
    bcast = plan_node("exchange.BroadcastExchangeExec", {},
                      [scan_node([dk[0], nm[0]], f_dim)])
    join = plan_node(
        "joins.BroadcastHashJoinExec",
        {"leftKeys": [attr("k", "long", 1)],
         "rightKeys": [attr("dk", "long", 3)],
         "joinType": "Inner", "buildSide": "BuildRight"},
        [scan_node([k[0], v[0]], f_big), bcast])
    res = convert_spark_plan(join)
    got = _run(res.plan)
    want = big.to_pandas().merge(dim.to_pandas(), left_on="k",
                                 right_on="dk")
    assert len(got) == len(want)
    assert res.output_names == ["k", "v", "dk", "name"]


def test_take_ordered_and_project(tmp_path):
    t = pa.table({"a": pa.array([5, 3, 9, 1, 7], type=pa.int64()),
                  "b": pa.array([50, 30, 90, 10, 70], type=pa.int64())})
    files = _write(tmp_path, t)
    a, b = attr("a", "long", 1), attr("b", "long", 2)
    plan = plan_node(
        "TakeOrderedAndProjectExec",
        {"limit": 3,
         "sortOrder": [sort_order(attr("a", "long", 1))],
         "projectList": [attr("b", "long", 2)]},
        [scan_node([a[0], b[0]], files)])
    res = convert_spark_plan(plan)
    got = _run(res.plan)
    assert got["b"].tolist() == [10, 30, 50]


def test_operator_gate_produces_never_convert_reason(tmp_path):
    t = pa.table({"x": pa.array([1], type=pa.int64())})
    files = _write(tmp_path, t)
    plan = plan_node("FilterExec",
                     {"condition": binexpr("GreaterThan",
                                           attr("x", "long", 1),
                                           lit("0", "long"))},
                     [scan_node([attr("x", "long", 1)[0]], files)])
    config.conf.set("auron.enable.filter", False)
    try:
        with pytest.raises(ConversionError, match="auron.enable.filter"):
            convert_spark_plan(plan)
    finally:
        config.conf.unset("auron.enable.filter")


def test_unsupported_expression_reports_class(tmp_path):
    t = pa.table({"x": pa.array([1], type=pa.int64())})
    files = _write(tmp_path, t)
    weird = [{"class": CAT + "ScalaUDF", "num-children": 1}] + \
        attr("x", "long", 1)
    plan = plan_node("ProjectExec", {"projectList": [weird]},
                     [scan_node([attr("x", "long", 1)[0]], files)])
    with pytest.raises(ConversionError, match="ScalaUDF"):
        convert_spark_plan(plan)


def test_wrappers_are_transparent(tmp_path):
    t = pa.table({"x": pa.array([1, 2], type=pa.int64())})
    files = _write(tmp_path, t)
    inner = scan_node([attr("x", "long", 1)[0]], files)
    wrapped = plan_node("WholeStageCodegenExec", {},
                        [plan_node("InputAdapter", {}, [inner])])
    res = convert_spark_plan(wrapped)
    assert res.plan["kind"] == "parquet_scan"
    got = _run(res.plan)
    assert got["x"].tolist() == [1, 2]


class TestReviewRegressions:
    def test_reordered_result_expressions_emit_projection(self, tmp_path):
        # resultExpressions [sum#10, k#1] vs physical [k, sum]: a parent
        # binding sum#10 must get the SUMS, not the keys
        t = pa.table({"k": pa.array([1, 1, 2], type=pa.int64()),
                      "v": pa.array([10.0, 20.0, 5.0])})
        files = _write(tmp_path, t)
        k, v = attr("k", "long", 1), attr("v", "double", 2)
        agg = plan_node(
            "aggregate.HashAggregateExec",
            {"groupingExpressions": [attr("k", "long", 1)],
             "aggregateExpressions": [agg_expr("Sum",
                                               attr("v", "double", 2),
                                               "Complete", 10)],
             "resultExpressions": [attr("s", "double", 10),
                                   attr("k", "long", 1)]},
            [scan_node([k[0], v[0]], files)])
        top = plan_node("ProjectExec",
                        {"projectList": [attr("s", "double", 10)]},
                        [agg])
        res = convert_spark_plan(top)
        got = _run(res.plan)
        assert sorted(got.iloc[:, 0].tolist()) == [5.0, 30.0]

    def test_pmod_maps_to_spark_pmod(self, tmp_path):
        t = pa.table({"x": pa.array([-7, 7], type=pa.int64())})
        files = _write(tmp_path, t)
        plan = plan_node(
            "ProjectExec",
            {"projectList": [alias(binexpr("Pmod", attr("x", "long", 1),
                                           lit("3", "long")), "m", 2)]},
            [scan_node([attr("x", "long", 1)[0]], files)])
        res = convert_spark_plan(plan)
        got = _run(res.plan)
        assert got["m"].tolist() == [2, 1]  # Spark pmod, not Java %

    def test_complete_mode_converts(self, tmp_path):
        t = pa.table({"k": pa.array([1, 1, 2], type=pa.int64()),
                      "v": pa.array([1.0, 2.0, 3.0])})
        files = _write(tmp_path, t)
        agg = plan_node(
            "aggregate.HashAggregateExec",
            {"groupingExpressions": [attr("k", "long", 1)],
             "aggregateExpressions": [agg_expr("Sum",
                                               attr("v", "double", 2),
                                               "Complete", 10)]},
            [scan_node([attr("k", "long", 1)[0],
                        attr("v", "double", 2)[0]], files)])
        res = convert_spark_plan(agg)
        got = _run(res.plan).sort_values("k")
        assert got.iloc[:, 1].tolist() == [3.0, 3.0]

    def test_mixed_agg_modes_rejected(self, tmp_path):
        t = pa.table({"k": pa.array([1], type=pa.int64()),
                      "v": pa.array([1.0])})
        files = _write(tmp_path, t)
        agg = plan_node(
            "aggregate.HashAggregateExec",
            {"groupingExpressions": [attr("k", "long", 1)],
             "aggregateExpressions": [
                 agg_expr("Sum", attr("v", "double", 2), "Partial", 10),
                 agg_expr("Sum", None, "PartialMerge", 11)]},
            [scan_node([attr("k", "long", 1)[0],
                        attr("v", "double", 2)[0]], files)])
        with pytest.raises(ConversionError, match="mixed aggregate modes"):
            convert_spark_plan(agg)


def test_catalyst_function_map_executes(tmp_path):
    """New Catalyst scalar-function mappings run end-to-end with Spark
    argument order (StringLocate is (substr, str) — the reverse of
    instr — and must NOT fall to the UDF wrapper)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.convert.spark import convert_spark_plan
    from blaze_tpu.itest import spark_plans as SP
    from blaze_tpu.plan import create_plan

    SP._reset_ids()
    t = pa.table({"s": pa.array(["abcb", "xyz", None])})
    p = str(tmp_path / "t.parquet")
    pq.write_table(t, p)
    tab = SP.Table("t", t, [[p]])

    CAT = SP.CAT
    locate = [{"class": CAT + "StringLocate", "num-children": 2}] + \
        SP.lit("b", "string") + tab.a("s").ref()
    initcap = [{"class": CAT + "InitCap", "num-children": 1}] + \
        tab.a("s").ref()
    pos = SP.A("pos", "integer")
    cap = SP.A("cap", "string")
    plan_json = SP.node(
        "ProjectExec",
        {"projectList": [SP.alias(locate, pos),
                         SP.alias(initcap, cap)]},
        [tab.scan()])
    res = convert_spark_plan(plan_json, num_partitions=1)
    ir = res.plan if hasattr(res, "plan") else res
    import json
    text = json.dumps(ir)
    assert '"locate"' in text and '"initcap"' in text, text[:400]
    assert "udf" not in text.lower()
    out = create_plan(ir).execute_collect().to_arrow()
    tbl = (pa.Table.from_batches([out])
           if isinstance(out, pa.RecordBatch) else out)
    assert tbl.column(0).to_pylist() == [2, 0, None]
    assert tbl.column(1).to_pylist() == ["Abcb", "Xyz", None]
