"""Bit-exactness tests against Spark-generated vectors.

Expected values mirror the reference's own test vectors
(ref: datafusion-ext-commons/src/spark_hash.rs:415-520, themselves generated
with Spark Murmur3_x86_32 / XxHash64) — behavioral parity, not a code port.
"""

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu.kernels import hashing as H


def _mm3(cols, n):
    return np.asarray(H.hash_columns(cols, seed=42, xp=np, algo="murmur3"))


def test_murmur3_i32_vectors():
    for value, expected in [(1, -559580957), (2, 1765031574),
                            (3, -1823081949), (4, -397064898)]:
        vals = np.array([value], dtype=np.int32)
        out = H.hash_columns([(vals, None, "int32")], xp=np)
        assert out[0] == expected


def test_murmur3_i8_promotes_to_int():
    vals = np.array([1, 0, -1, 127, -128], dtype=np.int8)
    out = H.hash_columns([(vals, None, "int8")], xp=np)
    expected = np.array([0xDEA578E3, 0x379FAE8F, 0xA0590E3D, 0x43B4D8ED, 0x422A1365],
                        dtype=np.uint32).view(np.int32)
    np.testing.assert_array_equal(out, expected)


def test_murmur3_i64_vectors():
    vals = np.array([1, 0, -1, np.iinfo(np.int64).max, np.iinfo(np.int64).min],
                    dtype=np.int64)
    out = H.hash_columns([(vals, None, "int64")], xp=np)
    expected = np.array([0x99F0149D, 0x9C67B85D, 0xC8008529, 0xA05B5D7B, 0xCD1E64FB],
                        dtype=np.uint32).view(np.int32)
    np.testing.assert_array_equal(out, expected)


def test_murmur3_string_vectors():
    arr = pa.array(["hello", "bar", "", "😁", "天地"])
    (mat, lengths), valid = H.string_column_to_padded_bytes(arr)
    out = H.hash_columns([(((mat, lengths)), valid, "utf8")], xp=np)
    expected = np.array([3286402344, 2486176763, 142593372, 885025535, 2395000894],
                        dtype=np.uint32).view(np.int32)
    np.testing.assert_array_equal(out, expected)


def test_xxhash64_i64_vectors():
    vals = np.array([1, 0, -1, np.iinfo(np.int64).max, np.iinfo(np.int64).min],
                    dtype=np.int64)
    out = H.hash_columns([(vals, None, "int64")], xp=np, algo="xxhash64")
    expected = np.array([-7001672635703045582, -5252525462095825812,
                         3858142552250413010, -3246596055638297850,
                         -8619748838626508300], dtype=np.int64)
    np.testing.assert_array_equal(out, expected)


def test_xxhash64_string_vectors():
    arr = pa.array(["hello", "bar", "", "😁", "天地"])
    (mat, lengths), valid = H.string_column_to_padded_bytes(arr)
    out = H.hash_columns([((mat, lengths), valid, "utf8")], xp=np, algo="xxhash64")
    expected = np.array([-4367754540140381902, -1798770879548125814,
                         -7444071767201028348, -6337236088984028203,
                         -235771157374669727], dtype=np.int64)
    np.testing.assert_array_equal(out, expected)


def test_xxhash64_long_strings_stripes():
    # >32 bytes exercises the stripe path
    s = ["a" * 100, "b" * 33, "c" * 32, "d" * 31, "x" * 64 + "tail"]
    arr = pa.array(s)
    (mat, lengths), valid = H.string_column_to_padded_bytes(arr)
    out = np.asarray(H.xxhash64_bytes(mat, lengths,
                                      np.full(5, 42, dtype=np.int64).view(np.uint64)))
    # cross-check against the reference python impl of xxh64 (hashlib lacks it),
    # so instead assert device/host agreement and determinism
    out_j = np.asarray(H.xxhash64_bytes(jnp.asarray(mat), jnp.asarray(lengths),
                                        jnp.full(5, 42, dtype=jnp.int64).view(jnp.uint64),
                                        xp=jnp))
    np.testing.assert_array_equal(out, out_j)


def test_null_rows_keep_seed():
    vals = np.array([1, 1], dtype=np.int32)
    valid = np.array([True, False])
    out = H.hash_columns([(vals, valid, "int32")], xp=np)
    assert out[0] == -559580957
    assert out[1] == 42  # untouched seed


def test_multi_column_chaining_matches_sequential():
    a = np.array([1, 2, 3], dtype=np.int32)
    b = np.array([10, 20, 30], dtype=np.int64)
    chained = H.hash_columns([(a, None, "int32"), (b, None, "int64")], xp=np)
    seeds = np.full(3, 42, dtype=np.uint32)
    h1 = H.murmur3_hash_int(a, seeds, np)
    h2 = H.murmur3_hash_long(b, h1, np)
    np.testing.assert_array_equal(chained, h2.view(np.int32))


def test_device_host_agreement():
    rng = np.random.default_rng(0)
    vals32 = rng.integers(-2**31, 2**31 - 1, size=1000, dtype=np.int64).astype(np.int32)
    vals64 = rng.integers(-2**62, 2**62, size=1000, dtype=np.int64)
    host = H.hash_columns([(vals32, None, "int32"), (vals64, None, "int64")], xp=np)
    dev = H.hash_columns([(jnp.asarray(vals32), None, "int32"),
                          (jnp.asarray(vals64), None, "int64")], xp=jnp)
    np.testing.assert_array_equal(host, np.asarray(dev))

    hostx = H.hash_columns([(vals64, None, "int64")], xp=np, algo="xxhash64")
    devx = H.hash_columns([(jnp.asarray(vals64), None, "int64")], xp=jnp,
                          algo="xxhash64")
    np.testing.assert_array_equal(hostx, np.asarray(devx))


def test_pmod_nonnegative():
    h = np.array([-7, 7, -200, 0], dtype=np.int32)
    out = H.pmod(h, 200, xp=np)
    assert out.tolist() == [193, 7, 0, 0]
    assert (np.asarray(H.pmod(jnp.asarray(h), 200)) == out).all()


def test_float_hash_negzero_and_nan():
    # -0.0 and 0.0 hash differently in raw bits; NaNs canonicalize
    f = np.array([np.nan, np.float32(np.nan)], dtype=np.float32)
    out = H.hash_columns([(f, None, "float32")], xp=np)
    assert out[0] == out[1]


def test_native_partition_kernel_bit_exact():
    """partition_kernel.cpp vs the numpy murmur3+pmod chain: identical
    pids across every fixed-width type, nulls, negatives, canonical
    NaN/-0.0 (pre-normalized, as the caller does)."""
    import pytest
    from blaze_tpu.bridge.native import get_partition_kernel
    from blaze_tpu.shuffle.partitioning import _native_pmod
    if get_partition_kernel() is None:
        pytest.skip("partition kernel not built")
    rng = np.random.default_rng(3)
    n = 10007
    cols = [
        (rng.integers(-(1 << 62), 1 << 62, n), None, "int64"),
        (rng.integers(-(1 << 30), 1 << 30, n).astype(np.int32),
         rng.random(n) > 0.1, "int32"),
        (rng.integers(-128, 127, n).astype(np.int8),
         rng.random(n) > 0.5, "int8"),
        (rng.random(n) * 1e6 - 5e5, rng.random(n) > 0.05, "float64"),
        ((rng.random(n).astype(np.float32)), None, "float32"),
        (rng.integers(0, 2, n).astype(bool), None, "bool"),
        (rng.integers(0, 40000, n).astype(np.int32), None, "date32"),
    ]
    from blaze_tpu.kernels import hashing as H
    for n_parts in (2, 7, 200):
        for subset in ([0], [1, 3], [0, 1, 2, 3, 4, 5, 6]):
            flat = [(cols[i][0], cols[i][1]) for i in subset]
            tids = [cols[i][2] for i in subset]
            flat = H.norm_float_keys(flat, tids, np)
            got = _native_pmod(flat, tids, n_parts)
            assert got is not None
            h = H.hash_columns(
                [(v, val, t) for (v, val), t in zip(flat, tids)],
                seed=42, xp=np, algo="murmur3")
            want = np.asarray(H.pmod(h, n_parts, xp=np)).astype(np.int32)
            np.testing.assert_array_equal(got, want)
