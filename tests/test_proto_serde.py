"""Protobuf plan-serde boundary tests.

The wire contract is the vendored auron.proto (TaskDefinition /
PhysicalPlanNode / PhysicalExprNode).  These tests check (a) IR dicts
round-trip through proto bytes, (b) decoded proto plans build the same
operator trees the JSON path builds, and (c) NativeExecutionRuntime accepts
raw TaskDefinition bytes end-to-end.
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.plan import create_plan
from blaze_tpu.plan.proto import auron_pb2 as pb
from blaze_tpu.plan.proto_serde import (expr_from_proto, expr_to_proto,
                                        partitioning_from_proto,
                                        partitioning_to_proto,
                                        plan_from_proto, plan_to_proto,
                                        scalar_from_proto, scalar_to_proto,
                                        schema_from_proto, schema_to_proto,
                                        task_definition_from_bytes,
                                        task_definition_to_bytes,
                                        type_from_proto, type_to_proto)


def _roundtrip_expr(d):
    return expr_from_proto(expr_to_proto(d))


def _roundtrip_plan(d):
    node = plan_to_proto(d)
    blob = node.SerializeToString()
    parsed = pb.PhysicalPlanNode()
    parsed.ParseFromString(blob)
    return plan_from_proto(parsed)


SCHEMA_D = {"fields": [
    {"name": "k", "type": {"id": "int64"}, "nullable": True},
    {"name": "v", "type": {"id": "float64"}, "nullable": True},
    {"name": "s", "type": {"id": "utf8"}, "nullable": True},
]}


class TestTypesAndScalars:
    @pytest.mark.parametrize("t", [
        {"id": "bool"}, {"id": "int8"}, {"id": "int16"}, {"id": "int32"},
        {"id": "int64"}, {"id": "float32"}, {"id": "float64"},
        {"id": "utf8"}, {"id": "binary"}, {"id": "date32"},
        {"id": "timestamp_us"}, {"id": "null"},
        {"id": "decimal", "precision": 12, "scale": 2},
    ])
    def test_type_roundtrip(self, t):
        assert type_from_proto(type_to_proto(t)) == t

    def test_nested_types(self):
        t = {"id": "list", "children": [
            {"name": "item", "type": {"id": "int64"}, "nullable": True}]}
        assert type_from_proto(type_to_proto(t)) == t
        t = {"id": "struct", "children": [
            {"name": "a", "type": {"id": "utf8"}, "nullable": True},
            {"name": "b", "type": {"id": "float64"}, "nullable": False}]}
        assert type_from_proto(type_to_proto(t)) == t

    def test_schema_roundtrip(self):
        assert schema_from_proto(schema_to_proto(SCHEMA_D)) == SCHEMA_D

    @pytest.mark.parametrize("value,t", [
        (42, {"id": "int64"}), (1.5, {"id": "float64"}),
        ("abc", {"id": "utf8"}), (True, {"id": "bool"}),
        (None, {"id": "int64"}), (b"\x00\x01", {"id": "binary"}),
    ])
    def test_scalar_roundtrip(self, value, t):
        got, got_t = scalar_from_proto(scalar_to_proto(value, t))
        assert got == value
        assert got_t == t

    def test_scalar_matches_reference_encoding(self):
        # the reference decodes ScalarValue as: Arrow IPC stream, batch 0,
        # column 0, row 0 (auron-planner/src/lib.rs:451-459)
        sv = scalar_to_proto(7, {"id": "int64"})
        import io
        with pa.ipc.open_stream(io.BytesIO(sv.ipc_bytes)) as r:
            rb = next(iter(r))
        assert rb.column(0)[0].as_py() == 7


class TestExprs:
    @pytest.mark.parametrize("d", [
        {"kind": "column", "name": "k"},
        {"kind": "column", "index": 3},
        {"kind": "literal", "value": 10, "type": {"id": "int64"}},
        {"kind": "binary", "op": ">",
         "l": {"kind": "column", "index": 0},
         "r": {"kind": "literal", "value": 5, "type": {"id": "int64"}}},
        {"kind": "is_null", "child": {"kind": "column", "index": 1}},
        {"kind": "is_not_null", "child": {"kind": "column", "index": 1}},
        {"kind": "not", "child": {"kind": "column", "index": 0}},
        {"kind": "in_list", "child": {"kind": "column", "index": 0},
         "values": [1, 2, 3], "negated": True},
        {"kind": "cast", "child": {"kind": "column", "index": 0},
         "type": {"id": "float64"}},
        {"kind": "try_cast", "child": {"kind": "column", "index": 2},
         "type": {"id": "int32"}},
        {"kind": "like", "child": {"kind": "column", "index": 2},
         "pattern": "a%", "negated": False, "case_insensitive": False},
        {"kind": "string_starts_with",
         "child": {"kind": "column", "index": 2}, "pattern": "pre"},
        {"kind": "string_ends_with",
         "child": {"kind": "column", "index": 2}, "pattern": "suf"},
        {"kind": "string_contains",
         "child": {"kind": "column", "index": 2}, "pattern": "mid"},
        {"kind": "scalar_function", "name": "upper",
         "args": [{"kind": "column", "index": 2}]},
        {"kind": "scalar_function", "name": "substring_index",
         "args": [{"kind": "column", "index": 2}]},  # ext-function path
        {"kind": "row_num"}, {"kind": "spark_partition_id"},
        {"kind": "monotonically_increasing_id"},
        {"kind": "randn", "seed": 7},
        {"kind": "bloom_filter_might_contain", "uuid": "bf-1",
         "value": {"kind": "column", "index": 0}},
        {"kind": "scalar_subquery", "uuid": "sq-9",
         "type": {"id": "int64"}},
        {"kind": "get_indexed_field",
         "child": {"kind": "column", "index": 0}, "index": 2},
        {"kind": "get_map_value",
         "child": {"kind": "column", "index": 0}, "key": "k1"},
        {"kind": "rlike", "child": {"kind": "column", "index": 2},
         "pattern": "^a.*", "case_insensitive": False},
    ])
    def test_expr_roundtrip(self, d):
        assert _roundtrip_expr(d) == d

    def test_case_roundtrip(self):
        d = {"kind": "case",
             "branches": [[{"kind": "binary", "op": "==",
                            "l": {"kind": "column", "index": 0},
                            "r": {"kind": "literal", "value": 1,
                                  "type": {"id": "int64"}}},
                           {"kind": "literal", "value": "one",
                            "type": {"id": "utf8"}}]],
             "else": {"kind": "literal", "value": "other",
                      "type": {"id": "utf8"}}}
        assert _roundtrip_expr(d) == d

    def test_case_with_operand_decodes_to_equality(self):
        e = pb.PhysicalExprNode()
        e.case_.expr.CopyFrom(expr_to_proto({"kind": "column", "index": 0}))
        wt = e.case_.when_then_expr.add()
        wt.when_expr.CopyFrom(expr_to_proto(
            {"kind": "literal", "value": 1, "type": {"id": "int64"}}))
        wt.then_expr.CopyFrom(expr_to_proto(
            {"kind": "literal", "value": 10, "type": {"id": "int64"}}))
        d = expr_from_proto(e)
        assert d["branches"][0][0]["op"] == "=="

    def test_coalesce_rides_the_scalar_function_enum(self):
        d = {"kind": "coalesce", "args": [{"kind": "column", "index": 0},
                                          {"kind": "column", "index": 1}]}
        assert _roundtrip_expr(d) == d

    def test_sc_and_decodes_to_binary(self):
        e = pb.PhysicalExprNode()
        e.sc_and_expr.left.CopyFrom(expr_to_proto({"kind": "column",
                                                   "index": 0}))
        e.sc_and_expr.right.CopyFrom(expr_to_proto({"kind": "column",
                                                    "index": 1}))
        assert expr_from_proto(e)["op"] == "and"

    def test_udf_wrapper_roundtrip(self):
        d = {"kind": "udf", "name": "my_fn",
             "args": [{"kind": "column", "index": 0}],
             "type": {"id": "int64"}}
        assert _roundtrip_expr(d) == d


class TestPartitioning:
    def test_hash(self):
        d = {"kind": "hash", "exprs": [{"kind": "column", "index": 0}],
             "num_partitions": 8}
        assert partitioning_from_proto(partitioning_to_proto(d)) == d

    def test_single_round_robin(self):
        assert partitioning_from_proto(
            partitioning_to_proto({"kind": "single"})) == {"kind": "single"}
        d = {"kind": "round_robin", "num_partitions": 4}
        assert partitioning_from_proto(partitioning_to_proto(d)) == d

    def test_range_bounds_survive(self):
        import base64
        import io
        rb = pa.record_batch([pa.array([10, 20, 30])], names=["b0"])
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, rb.schema) as w:
            w.write_batch(rb)
        d = {"kind": "range",
             "specs": [{"expr": {"kind": "column", "index": 0},
                        "descending": False, "nulls_first": True}],
             "num_partitions": 4,
             "bounds_ipc": base64.b64encode(sink.getvalue()).decode()}
        got = partitioning_from_proto(partitioning_to_proto(d))
        with pa.ipc.open_stream(io.BytesIO(
                base64.b64decode(got["bounds_ipc"]))) as r:
            got_rb = next(iter(r))
        assert got_rb.column(0).to_pylist() == [10, 20, 30]
        assert got["specs"] == d["specs"]


def _q01ish_plan_dict(path):
    scan = {"kind": "parquet_scan", "schema": SCHEMA_D,
            "file_groups": [[path]]}
    flt = {"kind": "filter", "input": scan,
           "predicates": [{"kind": "binary", "op": ">",
                           "l": {"kind": "column", "name": "k"},
                           "r": {"kind": "literal", "value": 2,
                                 "type": {"id": "int64"}}}]}
    agg = {"kind": "hash_agg", "input": flt,
           "groupings": [{"expr": {"kind": "column", "name": "s"},
                          "name": "s"}],
           "aggs": [{"fn": "sum", "mode": "partial", "name": "v_sum",
                     "args": [{"kind": "column", "name": "v"}]}]}
    return agg


class TestPlans:
    def test_scan_filter_agg_roundtrip(self):
        d = _q01ish_plan_dict("/tmp/x.parquet")
        got = _roundtrip_plan(d)
        assert got["kind"] == "hash_agg"
        assert got["groupings"][0]["name"] == "s"
        assert got["aggs"][0] == d["aggs"][0]
        flt = got["input"]
        assert flt["predicates"] == d["input"]["predicates"]
        scan = flt["input"]
        assert scan["schema"] == SCHEMA_D
        assert scan["file_groups"] == [["/tmp/x.parquet"]]

    def test_merge_mode_rebinds_acc_columns_positionally(self):
        # partial output layout: [s, v_sum] -> final agg's acc col is idx 1
        d = {"kind": "hash_agg",
             "input": {"kind": "ipc_reader", "resource_id": "r1",
                       "schema": {"fields": [
                           {"name": "s", "type": {"id": "utf8"},
                            "nullable": True},
                           {"name": "v_sum", "type": {"id": "float64"},
                            "nullable": True}]},
                       "num_partitions": 1},
             "groupings": [{"expr": {"kind": "column", "index": 0},
                            "name": "s"}],
             "aggs": [{"fn": "sum", "mode": "final", "name": "v_sum",
                       "args": [{"kind": "column", "index": 1}]}]}
        got = _roundtrip_plan(d)
        assert got["aggs"][0]["args"] == [{"kind": "column", "index": 1}]

    def test_avg_merge_claims_two_acc_columns(self):
        d = {"kind": "hash_agg",
             "input": {"kind": "ipc_reader", "resource_id": "r1",
                       "schema": SCHEMA_D, "num_partitions": 1},
             "groupings": [{"expr": {"kind": "column", "index": 0},
                            "name": "k"}],
             "aggs": [{"fn": "avg", "mode": "final", "name": "a",
                       "args": [{"kind": "column", "index": 1},
                                {"kind": "column", "index": 2}]},
                      {"fn": "count", "mode": "final", "name": "c",
                       "args": [{"kind": "column", "index": 3}]}]}
        got = _roundtrip_plan(d)
        assert got["aggs"][0]["args"] == [{"kind": "column", "index": 1},
                                          {"kind": "column", "index": 2}]
        assert got["aggs"][1]["args"] == [{"kind": "column", "index": 3}]

    def test_joins_roundtrip(self):
        reader = {"kind": "ipc_reader", "resource_id": "r", "schema":
                  SCHEMA_D, "num_partitions": 2}
        for kind in ("hash_join", "broadcast_join", "sort_merge_join"):
            d = {"kind": kind, "left": reader, "right": reader,
                 "left_keys": [{"kind": "column", "index": 0}],
                 "right_keys": [{"kind": "column", "index": 0}],
                 "join_type": "left_semi"}
            if kind != "sort_merge_join":
                d["build_side"] = "right"
            if kind == "broadcast_join":
                d["broadcast_id"] = "b-1"
            got = _roundtrip_plan(d)
            assert got["kind"] == kind
            assert got["join_type"] == "left_semi"
            assert got["left_keys"] == d["left_keys"]

    def test_window_roundtrip(self):
        d = {"kind": "window",
             "input": {"kind": "ipc_reader", "resource_id": "r",
                       "schema": SCHEMA_D, "num_partitions": 1},
             "functions": [
                 {"kind": "row_number", "name": "rn"},
                 {"kind": "rank", "name": "rk"},
                 {"kind": "lead", "name": "ld", "offset": 2,
                  "expr": {"kind": "column", "index": 1}},
                 {"kind": "lag", "name": "lg", "offset": 1,
                  "expr": {"kind": "column", "index": 1}},
                 {"kind": "nth_value", "name": "nv", "n": 3,
                  "expr": {"kind": "column", "index": 1}},
                 {"kind": "agg", "fn": "sum", "name": "ws",
                  "args": [{"kind": "column", "index": 1}]}],
             "partition_by": [{"kind": "column", "index": 0}],
             "order_by": [{"expr": {"kind": "column", "index": 1},
                           "descending": True, "nulls_first": False}],
             "group_limit": 5}
        got = _roundtrip_plan(d)
        assert [f["kind"] for f in got["functions"]] == \
            [f["kind"] for f in d["functions"]]
        assert got["functions"][2]["offset"] == 2
        assert got["functions"][3]["offset"] == 1
        assert got["functions"][4]["n"] == 3
        assert got["group_limit"] == 5
        assert got["order_by"] == d["order_by"]

    def test_generate_sort_limit_union_roundtrip(self):
        reader = {"kind": "ipc_reader", "resource_id": "r",
                  "schema": SCHEMA_D, "num_partitions": 1}
        gen = {"kind": "generate", "input": reader,
               "generator": {"kind": "explode",
                             "child": {"kind": "column", "index": 0},
                             "outer": True},
               "required_child_output": ["k", "v"]}
        got = _roundtrip_plan(gen)
        assert got["generator"]["kind"] == "explode"
        assert got["generator"]["outer"] is True
        assert got["required_child_output"] == ["k", "v"]

        srt = {"kind": "sort", "input": reader,
               "specs": [{"expr": {"kind": "column", "index": 0},
                          "descending": False, "nulls_first": True}],
               "fetch": 10}
        got = _roundtrip_plan(srt)
        assert got["fetch"] == 10 and got["specs"] == srt["specs"]

        lim = {"kind": "limit", "input": reader, "limit": 7, "offset": 2}
        got = _roundtrip_plan(lim)
        assert got["limit"] == 7 and got["offset"] == 2

        un = {"kind": "union", "inputs": [reader, reader]}
        got = _roundtrip_plan(un)
        assert len(got["inputs"]) == 2

    def test_shuffle_writer_roundtrip(self):
        d = {"kind": "shuffle_writer",
             "input": {"kind": "ipc_reader", "resource_id": "r",
                       "schema": SCHEMA_D, "num_partitions": 1},
             "partitioning": {"kind": "hash",
                              "exprs": [{"kind": "column", "index": 0}],
                              "num_partitions": 4},
             "data_file": "/tmp/s.data", "index_file": "/tmp/s.index"}
        got = _roundtrip_plan(d)
        assert got == d

    def test_expand_roundtrip(self):
        d = {"kind": "expand",
             "input": {"kind": "ipc_reader", "resource_id": "r",
                       "schema": SCHEMA_D, "num_partitions": 1},
             "projections": [
                 [{"kind": "column", "index": 0},
                  {"kind": "literal", "value": None, "type": {"id": "null"}}],
                 [{"kind": "column", "index": 0},
                  {"kind": "column", "index": 1}]],
             "names": ["k", "g"]}
        got = _roundtrip_plan(d)
        assert got["projections"] == d["projections"]
        assert got["names"] == d["names"]


class TestTaskDefinition:
    def test_bytes_roundtrip(self):
        td = {"stage_id": 3, "partition_id": 1, "task_attempt_id": 99,
              "plan": _q01ish_plan_dict("/tmp/x.parquet")}
        blob = task_definition_to_bytes(td)
        got = task_definition_from_bytes(blob)
        assert got["stage_id"] == 3
        assert got["partition_id"] == 1
        assert got["task_attempt_id"] == 99
        assert got["plan"]["kind"] == "hash_agg"

    def test_runtime_accepts_raw_proto_bytes(self, tmp_path):
        from blaze_tpu.bridge.runtime import NativeExecutionRuntime
        t = pa.table({"k": pa.array([1, 2, 3, 4, 5], type=pa.int64()),
                      "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0]),
                      "s": pa.array(["a", "b", "a", "b", "a"])})
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path)
        td = {"stage_id": 0, "partition_id": 0,
              "plan": _q01ish_plan_dict(path)}
        blob = task_definition_to_bytes(td)
        rt = NativeExecutionRuntime(blob).start()
        try:
            batches = list(rt.batches())
        finally:
            rt.finalize()
        out = pa.Table.from_batches(batches).to_pydict()
        # rows with k > 2: (3.0, a), (4.0, b), (5.0, a)
        got = dict(zip(out["s"], out["v_sum.sum"]))
        assert got == {"a": 8.0, "b": 4.0}

    def test_decoded_plan_builds_same_operator_tree_as_json(self, tmp_path):
        t = pa.table({"k": pa.array([1, 5, 9], type=pa.int64()),
                      "v": pa.array([1.0, 2.0, 3.0]),
                      "s": pa.array(["x", "y", "x"])})
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path)
        d = _q01ish_plan_dict(path)
        via_json = create_plan(d)
        via_proto = create_plan(_roundtrip_plan(d))
        assert type(via_json) is type(via_proto)
        assert via_json.schema.names == via_proto.schema.names
        j = [b.to_arrow() for b in via_json.execute(0)]
        p = [b.to_arrow() for b in via_proto.execute(0)]
        assert pa.Table.from_batches(j).equals(pa.Table.from_batches(p))


class TestReviewRegressions:
    def test_right_sided_semi_anti_refuse_to_encode(self):
        reader = {"kind": "ipc_reader", "resource_id": "r",
                  "schema": SCHEMA_D, "num_partitions": 1}
        d = {"kind": "hash_join", "left": reader, "right": reader,
             "left_keys": [{"kind": "column", "index": 0}],
             "right_keys": [{"kind": "column", "index": 0}],
             "join_type": "right_semi", "build_side": "left"}
        with pytest.raises(ValueError, match="no wire encoding"):
            plan_to_proto(d)

    def test_nth_value_ignore_nulls_roundtrip(self):
        d = {"kind": "window",
             "input": {"kind": "ipc_reader", "resource_id": "r",
                       "schema": SCHEMA_D, "num_partitions": 1},
             "functions": [{"kind": "nth_value", "name": "nv", "n": 2,
                            "ignore_nulls": True,
                            "expr": {"kind": "column", "index": 1}}],
             "partition_by": [], "order_by": []}
        got = _roundtrip_plan(d)
        assert got["functions"][0]["ignore_nulls"] is True
        assert got["functions"][0]["n"] == 2


class TestNullAwareAnti:
    def _run(self, left_rows, right_rows):
        from blaze_tpu.ops import MemoryScanExec
        from blaze_tpu.ops.joins import JoinType
        from blaze_tpu.ops.joins.exec import BroadcastJoinExec
        from blaze_tpu.exprs import col
        lt = pa.table({"x": pa.array(left_rows, type=pa.int64())})
        rt_ = pa.table({"y": pa.array(right_rows, type=pa.int64())})
        j = BroadcastJoinExec(
            MemoryScanExec.from_arrow(lt), MemoryScanExec.from_arrow(rt_),
            [col(0)], [col(0)], JoinType.LEFT_ANTI, build_side="right",
            null_aware_anti=True)
        out = [b.compact().to_arrow() for b in j.execute(0)]
        if not out:
            return []
        return pa.Table.from_batches(out)["x"].to_pylist()

    def test_null_in_build_rejects_everything(self):
        assert self._run([1, 2, None], [2, None]) == []

    def test_null_probe_keys_never_pass(self):
        assert self._run([1, 2, None], [2, 3]) == [1]

    def test_empty_build_keeps_all_rows_even_null(self):
        # x NOT IN (empty set) is TRUE for every x, including NULL
        assert self._run([1, None], []) == [1, None]


class TestNthValueIgnoreNulls:
    def test_nth_non_null_per_partition(self):
        from blaze_tpu.ops import MemoryScanExec, WindowExec
        from blaze_tpu.ops.window import NthValueFunc
        from blaze_tpu.exprs import col
        t = pa.table({"p": pa.array([1, 1, 1, 2, 2], type=pa.int64()),
                      "v": pa.array([None, 10, 20, None, 30],
                                    type=pa.int64())})
        w = WindowExec(
            MemoryScanExec.from_arrow(t),
            [NthValueFunc("nv", col(1), 2, ignore_nulls=True)],
            [col(0)], [])
        out = pa.Table.from_batches(
            [b.compact().to_arrow() for b in w.execute(0)])
        # partition 1: 2nd non-null = 20; partition 2: only one non-null
        assert out["nv"].to_pylist() == [20, 20, 20, None, None]


class TestReviewRegressions2:
    def test_regex_imatch_decodes_case_insensitive(self):
        e = pb.PhysicalExprNode()
        e.binary_expr.op = "RegexIMatch"
        e.binary_expr.l.CopyFrom(expr_to_proto({"kind": "column",
                                                "index": 0}))
        e.binary_expr.r.literal.CopyFrom(
            scalar_to_proto("^ab", {"id": "utf8"}))
        d = expr_from_proto(e)
        assert d["case_insensitive"] is True
        from blaze_tpu.plan.exprs import expr_from_dict
        rl = expr_from_dict(d)
        assert rl.case_insensitive is True

    def test_string_concat_decodes_to_concat_fn(self):
        e = pb.PhysicalExprNode()
        e.binary_expr.op = "StringConcat"
        e.binary_expr.l.CopyFrom(expr_to_proto({"kind": "column",
                                                "index": 0}))
        e.binary_expr.r.CopyFrom(expr_to_proto({"kind": "column",
                                                "index": 1}))
        d = expr_from_proto(e)
        assert d == {"kind": "scalar_function", "name": "concat",
                     "args": [{"kind": "column", "index": 0},
                              {"kind": "column", "index": 1}]}

    def test_multi_group_scan_refuses_to_encode(self):
        d = {"kind": "parquet_scan", "schema": SCHEMA_D,
             "file_groups": [["a.parquet"], ["b.parquet"]]}
        with pytest.raises(ValueError, match="ONE file group"):
            plan_to_proto(d)

    def test_broadcast_build_map_gets_cache_id(self):
        from blaze_tpu.ops.joins.exec import BuildHashMapExec
        reader = {"kind": "ipc_reader", "resource_id": "r",
                  "schema": SCHEMA_D, "num_partitions": 1}
        d = {"kind": "broadcast_join", "left": reader,
             "right": {"kind": "broadcast_join_build_hash_map",
                       "input": reader,
                       "keys": [{"kind": "column", "index": 0}]},
             "left_keys": [{"kind": "column", "index": 0}],
             "right_keys": [{"kind": "column", "index": 0}],
             "join_type": "inner", "build_side": "right",
             "broadcast_id": "bc-7"}
        plan = create_plan(d)
        build = plan.children[1]
        assert isinstance(build, BuildHashMapExec)
        assert build.cache_id == "bc-7"


def test_bnlj_rides_the_wire_as_keyless_broadcast_join():
    """broadcast_nested_loop_join has no dedicated proto node (matching
    the reference's PhysicalPlanType oneof); it encodes as a keyless
    broadcast_join and decodes back (review/report-caught: the wire tier
    crashed on q24's BNLJ scalar-threshold stage)."""
    import pytest
    from blaze_tpu.plan.proto_serde import plan_from_proto, plan_to_proto
    mem = {"kind": "empty_partitions", "num_partitions": 1,
           "schema": {"fields": [
               {"name": "a", "type": {"id": "int64"}, "nullable": True}]}}
    d = {"kind": "broadcast_nested_loop_join", "left": mem, "right": mem,
         "left_keys": [], "right_keys": [], "join_type": "inner",
         "build_side": "right"}
    back = plan_from_proto(plan_to_proto(d))
    assert back["kind"] == "broadcast_nested_loop_join"
    assert back["join_type"] == "inner"
    # an INNER residual condition lifts into a filter over the cross
    # product (wire-equivalent); outer variants are rejected
    filt = {"kind": "binary", "op": ">",
            "l": {"kind": "column", "index": 0},
            "r": {"kind": "literal", "value": 0, "type": {"id": "int64"}}}
    lifted = plan_from_proto(plan_to_proto(dict(d, join_filter=filt)))
    assert lifted["kind"] == "filter"
    assert lifted["input"]["kind"] == "broadcast_nested_loop_join"
    with pytest.raises(ValueError, match="no wire encoding"):
        plan_to_proto(dict(d, join_type="left", join_filter=filt))


def test_generate_required_cols_survive_the_wire(tmp_path):
    """generate's `required_cols` (index form) must translate to the
    wire's name-based required_child_output — an empty list decodes as
    'keep no child columns' and silently narrows the output schema
    (wire-report-caught on gq1)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.plan import create_plan
    from blaze_tpu.plan.proto_serde import plan_from_proto, plan_to_proto
    t = pa.table({"sk": pa.array([1, 2]),
                  "items": pa.array([[1, 2], [3]],
                                    type=pa.list_(pa.int64()))})
    p = str(tmp_path / "g.parquet")
    pq.write_table(t, p)
    ir = {"kind": "generate",
          "generator": {"kind": "posexplode",
                        "child": {"kind": "column", "name": "items"},
                        "outer": False},
          "required_cols": [0],
          "input": {"kind": "parquet_scan", "schema": {"fields": [
              {"name": "sk", "type": {"id": "int64"}, "nullable": True},
              {"name": "items", "type": {"id": "list", "children": [
                  {"name": "item", "type": {"id": "int64"},
                   "nullable": True}]}, "nullable": True}]},
              "file_groups": [[p]]}}
    direct = create_plan(ir)
    wired = create_plan(plan_from_proto(plan_to_proto(ir)))
    assert [f.name for f in wired.schema] == \
        [f.name for f in direct.schema]
    assert len(wired.schema) == 3  # sk + pos + exploded element
