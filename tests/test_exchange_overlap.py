"""Overlapped device exchange (ISSUE 18): the dispatch/drain split of
the cached shard_map collective, the staged scheduler's overlap path
(bit-identical blocks, wholesale fallback, clean cancellation, one
compile per ladder rung), the process-per-device worker pinning with
real child CPU accounting, and the compressed worker/RSS wire frames."""

import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.memory import MemManager
from blaze_tpu.parallel.stage import DeviceExchange
from blaze_tpu.plan.stages import DagScheduler

SENT = -(1 << 60)


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    try:
        yield
    finally:
        faults.clear()


@pytest.fixture
def staged_device():
    """Force the staged DAG path and the device shuffle lane."""
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)
        config.conf.unset(config.SHUFFLE_DEVICE.key)


@pytest.fixture
def overlap_on(staged_device):
    config.conf.set(config.EXCHANGE_OVERLAP_ENABLE.key, True)
    try:
        yield
    finally:
        config.conf.unset(config.EXCHANGE_OVERLAP_ENABLE.key)


def _two_stage_plan(tmp_path, n=8000, n_reduce=3, n_files=4):
    """hash_agg(final) <- hash exchange <- hash_agg(partial) <- scan,
    split over `n_files` map tasks so the overlap window sees several
    dispatches in flight."""
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 200, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    per = n // n_files
    paths = []
    for i in range(n_files):
        p = str(tmp_path / f"in-{i}.parquet")
        pq.write_table(t.slice(i * per, per), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[p] for p in paths]}}}}


def _sorted_df(tbl):
    return tbl.to_pandas().sort_values("k").reset_index(drop=True)


# -- overlap scheduler: identity, fallback, cancellation, recompiles --------

def test_overlap_defaults_off():
    """Default-off acceptance: without the knob the synchronous path
    runs and nothing overlapped is recorded."""
    assert config.EXCHANGE_OVERLAP_ENABLE.get() is False


def test_overlap_bit_identical_to_sync(tmp_path, device_mesh,
                                       staged_device):
    """Same plan, same seeds, same grow schedule: the overlapped
    exchange must publish byte-identical results (float sums are exact
    only if the per-partition concat order matches the sync merge)."""
    plan = _two_stage_plan(tmp_path)
    sync = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-sync")).run_collect(plan))

    config.conf.set(config.EXCHANGE_OVERLAP_ENABLE.key, True)
    try:
        xla_stats.reset()
        sched = DagScheduler(work_dir=str(tmp_path / "dag-overlap"))
        got = _sorted_df(sched.run_collect(plan))
    finally:
        config.conf.unset(config.EXCHANGE_OVERLAP_ENABLE.key)

    assert got.equals(sync)
    ss = xla_stats.shuffle_stats()
    assert ss["shuffle_device_overlap_exchanges"] >= 1
    assert ss["shuffle_device_fallbacks"] == 0
    assert ss["shuffle_host_bytes"] == 0
    assert all(v == [] for v in sched.leak_report().values())


def test_overlap_fault_falls_back_wholesale(tmp_path, device_mesh,
                                            overlap_on):
    """A device-collective fault mid-overlap is deferred past the wave
    and downgrades the WHOLE stage to the file shuffle — never a
    per-task retry, never divergence."""
    plan = _two_stage_plan(tmp_path)
    config.conf.set(config.SHUFFLE_DEVICE.key, "off")
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-file")).run_collect(plan))
    config.conf.set(config.SHUFFLE_DEVICE.key, "on")

    xla_stats.reset()
    sched = DagScheduler(work_dir=str(tmp_path / "dag-fault"))
    with faults.scoped(("device-collective", dict(at=(1,)))):
        got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)
    assert xla_stats.shuffle_stats()["shuffle_device_fallbacks"] >= 1
    assert all(v == [] for v in sched.leak_report().values())


def test_overlap_cancellation_mid_chunk_leaves_no_leaks(
        tmp_path, device_mesh, overlap_on, monkeypatch):
    """Cancel the query BETWEEN a ticket's dispatch and its drain: the
    wave unwinds, the drainer thread is joined, and leak_report is
    clean — no shuffle files, resources or rss roots left behind."""
    from blaze_tpu.serving.context import QueryCancelled, QueryContext

    ctx = QueryContext("q-cancel-overlap")
    orig = DeviceExchange.dispatch

    def dispatch_then_cancel(self, *args, **kwargs):
        ticket = orig(self, *args, **kwargs)
        ctx.cancel("mid-chunk cancellation test")
        return ticket

    monkeypatch.setattr(DeviceExchange, "dispatch", dispatch_then_cancel)
    plan = _two_stage_plan(tmp_path)
    sched = DagScheduler(work_dir=str(tmp_path / "dag-cancel"),
                         query_ctx=ctx)
    with pytest.raises(QueryCancelled):
        sched.run_collect(plan)
    report = sched.leak_report()
    assert all(v == [] for v in report.values()), report
    assert not [t for t in threading.enumerate()
                if t.name.startswith("exchange-drain-")]


def _kv_columns(n=5000, seed=3):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 200, n, dtype=np.int64)
    kv = rng.random(n) > 0.1
    v = rng.random(n)
    return ([k, v], [kv, np.ones(n, dtype=bool)])


def _multiset(datas, valids):
    k, v = datas
    kval, _ = valids
    return sorted((int(k[i]) if kval[i] else SENT, float(v[i]))
                  for i in range(len(k)))


def test_dispatch_drain_compiles_once_per_rung(device_mesh):
    """The async split must NOT cost extra traces: dispatch+drain of
    the same shape signature reuses the one cached shard_map program
    per ladder rung, and routes rows exactly like `exchange`."""
    from blaze_tpu.parallel.stage import _exchange_program
    _exchange_program.cache_clear()  # order-independent: force a trace
    cols, valids = _kv_columns()
    ex = DeviceExchange(device_mesh)
    ref = ex.exchange(cols, valids, [0], 3)

    def compiles():
        kernels = xla_stats.compile_report()["kernels"]
        return kernels.get("mesh.exchange_rows", {}).get("compiles", 0)

    c0 = compiles()
    assert c0 >= 1  # the warm exchange above compiled the rung
    for _ in range(2):
        parts = ex.drain(ex.dispatch(cols, valids, [0], 3))
        assert len(parts) == 3
        for r in range(3):
            assert _multiset(*parts[r]) == _multiset(*ref[r])
    assert compiles() == c0


def test_exchange_wire_cost_accounting():
    """Shared by the sync and overlapped paths: one collective per
    staged buffer (data + validity per column, plus the pid rider and
    the row mask), n_dev^2 x capacity slots moved."""
    from blaze_tpu.parallel.collective import exchange_wire_cost
    moved, colls = exchange_wire_cost(4, 128, ("int64", "float64"))
    assert colls == 2 * 2 + 2
    per_slot = 8 + 8 + 2 + 4 + 1  # data + valids + pid(int32) + mask
    assert moved == 4 * 4 * 128 * per_slot


# -- process-per-device pinning + child CPU accounting ----------------------

def test_child_env_pins_exactly_one_device(monkeypatch):
    from blaze_tpu.parallel.workers import (_child_device_spec, _Slot,
                                            WorkerPool)
    slot = _Slot(3)
    assert WorkerPool._child_env(slot) is None  # knob off: inherit parent
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=8")
    config.conf.set(config.WORKERS_PIN_DEVICES.key, True)
    try:
        env = WorkerPool._child_env(slot)
    finally:
        config.conf.unset(config.WORKERS_PIN_DEVICES.key)
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=1" in env["XLA_FLAGS"]
    assert "device_count=8" not in env["XLA_FLAGS"]
    assert env["BLAZE_WORKER_DEVICE_SLOT"] == "3"

    for k in ("JAX_PLATFORMS", "XLA_FLAGS", "BLAZE_WORKER_DEVICE_SLOT"):
        monkeypatch.setenv(k, env[k])
    spec = _child_device_spec()
    assert spec == {"slot": 3, "platform": "cpu", "local_devices": 1}


def test_worker_pool_pins_devices_and_accounts_cpu():
    """End to end through the CRC32C worker protocol: the hello frame
    carries the child's device_spec, the result frame carries its
    cpu_ns, and both surface in pool.health() / xla_stats."""
    from blaze_tpu.parallel.workers import WorkerPool
    config.conf.set(config.WORKERS_PIN_DEVICES.key, True)
    pool = None
    before = xla_stats.snapshot()
    try:
        pool = WorkerPool(count=1, liveness_ms=60000).start()
        res = pool.run(
            {"fn": "blaze_tpu.parallel.workers:_task_device_shard",
             "args": (20000, 64, 2, 0)}, timeout_s=180)
        assert res["devices"] == 1
        assert res["platform"] == "cpu"
        assert res["cpu_s"] > 0
        health = pool.health()[0]
        assert health["device_spec"]["local_devices"] == 1
        assert health["cpu_s"] > 0
    finally:
        if pool is not None:
            pool.shutdown(wait=False)
        config.conf.unset(config.WORKERS_PIN_DEVICES.key)
    delta = xla_stats.delta(before)
    assert delta["worker_cpu_ns"] > 0


# -- compressed wire frames (worker protocol + RSS puts) --------------------

def _configured_codec():
    from blaze_tpu.shuffle.ipc import CODEC_RAW, _get_codec
    codec = _get_codec()
    if codec == CODEC_RAW:
        pytest.skip("no compression codec available in this build")
    return codec


def test_control_frame_codec_roundtrip():
    """The frame byte keys the decode, so old and new peers mix: a
    compressed frame round-trips, and a payload compression would GROW
    (or a raw request) stays a raw CRC frame."""
    from blaze_tpu.shuffle import rss
    from blaze_tpu.shuffle.ipc import (CODEC_RAW, _HEADER,
                                       pack_control_frame)
    codec = _configured_codec()
    payload = b"overlapped exchange " * 512
    frame = pack_control_frame(payload, codec)
    assert len(frame) < len(payload)
    assert (frame[0] & 0x7F) == codec
    assert rss._unpack_put(frame) == payload

    tiny = b"\x00\x01\x02"
    raw = pack_control_frame(tiny, codec)  # growth: falls back to raw
    assert (raw[0] & 0x7F) == CODEC_RAW
    assert rss._unpack_put(raw) == tiny
    assert raw[_HEADER.size + 4:] == tiny


def test_rss_pushz_roundtrip_and_accounting():
    from blaze_tpu.shuffle import rss
    _configured_codec()
    config.conf.set(config.IO_COMPRESSION_WORKER_FRAMES.key, True)
    before = xla_stats.snapshot()
    try:
        payload = b"rss partition put " * 512
        wire, suffix = rss._pack_put(payload)
        assert suffix == "pushz" and len(wire) < len(payload)
        assert rss._unpack_put(wire) == payload
        tiny_wire, tiny_suffix = rss._pack_put(b"xy")
        assert tiny_suffix == "push" and tiny_wire == b"xy"
    finally:
        config.conf.unset(config.IO_COMPRESSION_WORKER_FRAMES.key)
    assert xla_stats.delta(before)["rss_put_compressed_bytes_saved"] > 0
    # the read side keys the unwrap on the committed suffix
    assert rss._FRAME.match("m1-a0-s2.pushz").group(4) == "z"
    assert rss._FRAME.match("m1-a0-s2.push").group(4) == ""


def test_worker_frames_stay_raw_by_default():
    from blaze_tpu.parallel.workers import _frame_codec
    from blaze_tpu.shuffle.ipc import CODEC_RAW
    assert _frame_codec() == CODEC_RAW


# -- observability: explain footer, sentinel directions, statstore ----------

def test_explain_footer_reports_overlap_and_compression(
        tmp_path, device_mesh, overlap_on):
    from blaze_tpu.plan.explain import QueryProfile
    xla_stats.reset()
    before = xla_stats.snapshot()
    plan = _two_stage_plan(tmp_path)
    sched = DagScheduler(work_dir=str(tmp_path / "dag"))
    sched.run_collect(plan)
    xla_stats.note_frame_compression("worker", 1024)
    xla_stats.note_frame_compression("rss", 2048)
    profile = QueryProfile(
        query_id="q-overlap", wall_ns=1, tree=sched.collect_metrics(),
        partitions=3, exec_mode="staged", xla=xla_stats.delta(before),
        kernels={}, placement="device", output_rows=0)
    text = profile.render_text()
    assert "shuffle: device=" in text
    assert "overlap: exchanges=" in text
    assert "barrier_idle=" in text
    assert "frame compression: worker=" in text


def test_sentinel_directions_for_new_metrics():
    from blaze_tpu.tools.sentinel import metric_direction
    assert metric_direction("legs.2.barrier_idle_s") == "lower"
    assert metric_direction("legs.2.dispatch_gap_s") == "lower"
    assert metric_direction("shuffle_barrier_idle_ns") == "lower"
    assert metric_direction("legs.2.speedup_vs_1") == "higher"
    assert metric_direction("legs.2.cpu_parallelism") == "higher"
    assert metric_direction("shuffle_device_overlap_exchanges") == "higher"
    assert metric_direction(
        "worker_frame_compressed_bytes_saved") == "higher"


def test_statstore_ingests_barrier_counters():
    from blaze_tpu.plan.statstore import INGEST_COUNTERS
    assert "shuffle_barrier_idle_ns" in INGEST_COUNTERS
    assert "shuffle_device_overlap_exchanges" in INGEST_COUNTERS
