"""Async pipeline executor semantics (ops/base.py PrefetchIterator):
ordering, exception propagation, clean close (no leaked threads),
synchronous degradation, and the default-on wiring at the IO edges."""

import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.bridge import xla_stats
from blaze_tpu.ops.base import PrefetchIterator, prefetch


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("blaze-prefetch")]


def _wait_no_threads(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = [t for t in _prefetch_threads() if t.is_alive()]
        if not alive:
            return True
        time.sleep(0.01)
    return False


def test_ordering_preserved():
    items = list(range(200))
    assert list(prefetch(iter(items), depth=3)) == items
    assert _wait_no_threads()


def test_transform_applied_on_worker():
    worker_threads = set()

    def xform(x):
        worker_threads.add(threading.current_thread().name)
        return x * 2

    out = list(prefetch(iter(range(50)), depth=2, transform=xform,
                        name="xform"))
    assert out == [x * 2 for x in range(50)]
    assert all(n.startswith("blaze-prefetch") for n in worker_threads)
    assert _wait_no_threads()


def test_exception_reraised_at_consumer_in_position():
    def gen():
        yield 1
        yield 2
        raise ValueError("decode failed")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(ValueError, match="decode failed"):
        next(it)
    # exhausted after the error; worker gone
    with pytest.raises(StopIteration):
        next(it)
    assert _wait_no_threads()


def test_transform_exception_propagates():
    def boom(x):
        if x == 3:
            raise RuntimeError("transform blew up")
        return x

    it = prefetch(iter(range(10)), depth=2, transform=boom)
    assert [next(it), next(it), next(it)] == [0, 1, 2]
    with pytest.raises(RuntimeError, match="transform blew up"):
        for _ in it:
            pass
    assert _wait_no_threads()


def test_close_drains_blocked_worker():
    produced = []

    def gen():
        for i in range(10_000):
            produced.append(i)
            yield i

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()
    assert _wait_no_threads(), "close() must join the worker"
    # bounded queue: the worker never ran away from the consumer
    assert len(produced) <= 10
    with pytest.raises(StopIteration):
        next(it)
    it.close()  # idempotent


def test_no_leaked_threads_after_full_consumption():
    for _ in range(5):
        assert len(list(prefetch(iter(range(100)), depth=4))) == 100
    assert _wait_no_threads()


def test_depth_zero_is_synchronous():
    base = len(_prefetch_threads())
    it = prefetch(iter(range(10)), depth=0, transform=lambda x: x + 1)
    assert len(_prefetch_threads()) == base, "depth=0 must not spawn"
    assert list(it) == list(range(1, 11))


def test_kill_switch_disables_thread():
    with config.scoped(**{"auron.tpu.io.prefetch": False}):
        base = len(_prefetch_threads())
        it = prefetch(iter(range(5)))
        assert len(_prefetch_threads()) == base
        assert list(it) == list(range(5))


def test_default_depth_from_config():
    with config.scoped(**{"auron.tpu.io.prefetch.depth": 3}):
        it = prefetch(iter(range(5)))
        assert it._queue is not None and it._queue.maxsize == 3
        assert list(it) == list(range(5))
        assert _wait_no_threads()


def test_prefetch_stats_counted():
    before = xla_stats.snapshot()
    list(prefetch(iter(range(20)), depth=2))
    d = xla_stats.delta(before)
    assert d["prefetch_batches"] == 20
    assert d["prefetch_wait_ns"] >= 0


def test_empty_source():
    assert list(prefetch(iter(()), depth=2)) == []
    assert _wait_no_threads()


# -- default-on wiring at the IO edges ---------------------------------------

def _parquet(tmp_path, n=3000):
    rng = np.random.default_rng(0)
    t = pa.table({"k": pa.array(rng.integers(0, 9, n)),
                  "v": pa.array(rng.random(n))})
    path = str(tmp_path / "t.parquet")
    import pyarrow.parquet as pq
    pq.write_table(t, path, row_group_size=700)
    return path, t


def test_parquet_scan_prefetches_by_default(tmp_path):
    from blaze_tpu.ops.scan import ParquetScanExec
    from blaze_tpu.schema import Schema
    path, t = _parquet(tmp_path)
    scan = ParquetScanExec(Schema.from_arrow(t.schema), [[path]],
                           batch_rows=512)
    before = xla_stats.snapshot()
    rows = sum(b.num_rows for b in scan.execute(0))
    assert rows == t.num_rows
    assert xla_stats.delta(before)["prefetch_batches"] > 0
    assert _wait_no_threads()


def test_parquet_scan_prefetch_kill_switch_matches(tmp_path):
    from blaze_tpu.ops.scan import ParquetScanExec
    from blaze_tpu.schema import Schema
    path, t = _parquet(tmp_path)

    def collect():
        scan = ParquetScanExec(Schema.from_arrow(t.schema), [[path]],
                               batch_rows=512)
        out = [b.compact().to_arrow() for b in scan.execute(0)]
        return pa.Table.from_batches([b for b in out if b.num_rows])

    on = collect()
    with config.scoped(**{"auron.tpu.io.prefetch": False}):
        before = xla_stats.snapshot()
        off = collect()
        assert xla_stats.delta(before)["prefetch_batches"] == 0
    assert on.equals(off)


def test_explain_analyze_surfaces_prefetch_stats(tmp_path):
    from blaze_tpu.ops.scan import ParquetScanExec
    from blaze_tpu.plan import explain_analyze
    from blaze_tpu.schema import Schema
    path, t = _parquet(tmp_path)
    scan = ParquetScanExec(Schema.from_arrow(t.schema), [[path]],
                           batch_rows=512)
    prof = explain_analyze(scan, record=False)
    assert prof.output_rows == t.num_rows
    assert prof.xla.get("prefetch_batches", 0) > 0
    assert "prefetch:" in prof.render_text()
    assert _wait_no_threads()


def test_shuffle_roundtrip_under_prefetch():
    """Map-side materialization + reduce-side IPC reads run through the
    prefetcher by default and stay byte-identical to the synchronous
    path."""
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.shuffle import HashPartitioning, LocalShuffleExchange

    rng = np.random.default_rng(1)
    t = pa.table({"k": pa.array(rng.integers(0, 32, 5000)),
                  "v": pa.array(rng.random(5000))})

    def run():
        scan = MemoryScanExec.from_arrow(t, num_partitions=2,
                                         batch_rows=700)
        ex = LocalShuffleExchange(scan, HashPartitioning([col(0, "k")], 4))
        parts = []
        for p in range(4):
            rows = [b.compact().to_arrow() for b in ex.execute(p)]
            tab = (pa.Table.from_batches([r for r in rows if r.num_rows],
                                         schema=ex.schema.to_arrow())
                   if rows else None)
            parts.append(tab.sort_by([("k", "ascending"),
                                      ("v", "ascending")])
                         if tab is not None else None)
        ex.cleanup()
        return parts

    before = xla_stats.snapshot()
    on = run()
    assert xla_stats.delta(before)["prefetch_batches"] > 0
    with config.scoped(**{"auron.tpu.io.prefetch": False}):
        off = run()
    for a, b in zip(on, off):
        assert (a is None) == (b is None)
        if a is not None:
            assert a.equals(b)
    assert _wait_no_threads()
