"""Auron-tab observability store (VERDICT r3 missing #6): per-query
conversion records with fallback reasons, served over the profiling
HTTP service as /auron (JSON) and /auron.html."""

import json
import urllib.request

import pytest

from blaze_tpu.bridge import ui
from blaze_tpu.memory import MemManager


@pytest.fixture(autouse=True)
def clean():
    MemManager.init(4 << 30)
    ui.reset()
    yield
    ui.reset()


def test_tagging_and_summary():
    from blaze_tpu.convert.strategy import NodeTag
    tag = NodeTag("SortExec", True, "", [
        NodeTag("MysteryExec", False, "unsupported operator", []),
        NodeTag("FilterExec", True, "", []),
    ])
    qid = ui.next_query_id()
    ui.record_conversion(qid, ["SortExec", "FilterExec"], [])
    ui.record_tagging(qid, tag)
    ui.record_completion(qid, 0.123)
    (e,) = ui.executions()
    assert e["native_nodes"] == 2
    assert e["fallbacks"] == [{"node": "MysteryExec",
                               "reason": "unsupported operator"}]
    assert e["wall_s"] == 0.123
    assert ui.fallback_summary() == {
        "MysteryExec: unsupported operator": 1}


def test_convert_spark_plan_records_automatically():
    from blaze_tpu.itest.spark_plans import SPARK_QUERIES
    from blaze_tpu.itest import generate
    from blaze_tpu.itest.tpcds_data import write_parquet_splits
    from blaze_tpu.convert.spark import convert_spark_plan
    import json as _json
    import tempfile
    builder, names = SPARK_QUERIES["q06"]
    tables = generate(names, scale=0.01)
    with tempfile.TemporaryDirectory() as tmp:
        paths = write_parquet_splits(tables, tmp, 2)
        plan_tpl, _oracle = builder(paths, tables, 2)
        convert_spark_plan(_json.loads(_json.dumps(plan_tpl)), 2)
    (e,) = ui.executions()
    assert e["native_nodes"] > 5


def test_http_endpoints_serve_the_tab():
    from blaze_tpu.bridge.profiling import (start_http_service,
                                            stop_http_service)
    ui.record_conversion(ui.next_query_id(), ["FilterExec"], [])
    port = start_http_service()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/auron") as r:
            data = json.loads(r.read())
        assert data["executions"][0]["native_nodes"] == 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/auron.html") as r:
            page = r.read().decode()
        assert "Auron SQL Executions" in page and "FilterExec" not in page
    finally:
        stop_http_service()
