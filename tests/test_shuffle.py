"""Shuffle tests: partitioning parity, .data/.index contract, exchange,
two-stage agg through a real shuffle (the spark-local analog, SURVEY.md §4).
"""

import io
import os
import struct

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import schema as S
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import col
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import AggExec, AggMode, MemoryScanExec, make_agg
from blaze_tpu.shuffle import (FileSegmentBlock, HashPartitioning,
                               IpcReaderExec, LocalShuffleExchange,
                               RangePartitioning, RoundRobinPartitioning,
                               ShuffleWriterExec, SinglePartitioning,
                               read_index_file, sample_range_bounds)
from blaze_tpu.bridge.resource import put_resource


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def test_hash_partition_ids_match_spark_pmod():
    """pmod(murmur3(seed42), n) — golden values from Spark's
    Murmur3_x86_32 via the validated hash kernels (tests/test_hashing.py)."""
    t = pa.table({"k": pa.array([1, 2, 3, 4, 5], type=pa.int64())})
    cb = ColumnBatch.from_arrow(t)
    p = HashPartitioning([col(0)], 4)
    ids = p.partition_ids(cb)
    from blaze_tpu.kernels import hashing as H
    import numpy as np
    want = H.pmod(H.hash_columns(
        [(np.array([1, 2, 3, 4, 5], dtype=np.int64), None, "int64")],
        seed=42, xp=np, algo="murmur3"), 4, xp=np)
    assert ids.tolist() == want.tolist()


def test_round_robin_spreads():
    t = pa.table({"k": pa.array(range(10))})
    p = RoundRobinPartitioning(3)
    cb = ColumnBatch.from_arrow(t)
    ids = p.partition_ids(cb)
    counts = np.bincount(ids, minlength=3)
    assert counts.max() - counts.min() <= 1
    # second batch continues the cursor
    ids2 = p.partition_ids(cb)
    assert ids2[0] == (ids[-1] + 1) % 3


def test_shuffle_writer_data_index_contract(tmp_path):
    rng = np.random.default_rng(0)
    n = 5000
    t = pa.table({"k": pa.array(rng.integers(0, 1000, n)),
                  "v": pa.array(rng.random(n))})
    scan = MemoryScanExec.from_arrow(t, batch_rows=512)
    data = str(tmp_path / "out.data")
    index = str(tmp_path / "out.index")
    w = ShuffleWriterExec(scan, HashPartitioning([col(0)], 8), data, index)
    list(w.execute(0))
    offsets = read_index_file(index)
    assert len(offsets) == 9
    assert offsets[0] == 0
    assert offsets[-1] == os.path.getsize(data)
    # read every partition back through file segments; total rows must match
    total = 0
    seen_keys = set()
    for p in range(8):
        put_resource("t1", [FileSegmentBlock(data, offsets[p],
                                             offsets[p + 1] - offsets[p])])
        reader = IpcReaderExec("t1", S.Schema.from_arrow(t.schema))
        got = reader.execute_collect().to_arrow()
        total += got.num_rows
        seen_keys.update(got.column("k").to_pylist())
    assert total == n
    assert seen_keys == set(t.column("k").to_pylist())


def test_shuffle_writer_spill(tmp_path):
    rng = np.random.default_rng(1)
    n = 40000
    t = pa.table({"k": pa.array(rng.integers(0, 100, n)),
                  "v": pa.array(rng.random(n))})
    MemManager.init(200_000)
    scan = MemoryScanExec.from_arrow(t, batch_rows=4096)
    data = str(tmp_path / "s.data")
    index = str(tmp_path / "s.index")
    w = ShuffleWriterExec(scan, HashPartitioning([col(0)], 4), data, index)
    list(w.execute(0))
    assert w.metrics.get("spill_count") >= 1
    offsets = read_index_file(index)
    total = 0
    for p in range(4):
        put_resource("t2", [FileSegmentBlock(data, offsets[p],
                                             offsets[p + 1] - offsets[p])])
        got = IpcReaderExec("t2", S.Schema.from_arrow(t.schema)) \
            .execute_collect()
        total += got.num_rows
    assert total == n


def test_two_stage_agg_through_exchange():
    """Partial agg -> hash exchange on keys -> final agg == pandas."""
    rng = np.random.default_rng(2)
    n = 30000
    t = pa.table({"k": pa.array(rng.integers(0, 200, n)),
                  "v": pa.array(rng.random(n))})
    scan = MemoryScanExec.from_arrow(t, num_partitions=4, batch_rows=1024)
    schema = S.Schema.from_arrow(t.schema)
    partial = AggExec(scan, [(col(0, "k"), "k")],
                      [(make_agg("sum", [col(1)]), AggMode.PARTIAL, "s"),
                       (make_agg("count", [col(1)]), AggMode.PARTIAL, "c")])
    exchange = LocalShuffleExchange(partial, HashPartitioning([col(0)], 3))
    final = AggExec(exchange, [(col(0, "k"), "k")],
                    [(make_agg("sum", [col(1)]), AggMode.PARTIAL_MERGE, "s"),
                     (make_agg("sum", [col(2)]), AggMode.PARTIAL_MERGE, "c")])
    got = final.execute_collect().to_arrow()
    want = t.to_pandas().groupby("k").agg(s=("v", "sum"), c=("v", "count"))
    assert got.num_rows == len(want)
    gd = dict(zip(got.column("k").to_pylist(), got.column("s.sum").to_pylist()))
    cd = dict(zip(got.column("k").to_pylist(), got.column("c.sum").to_pylist()))
    for k, row in want.iterrows():
        assert gd[k] == pytest.approx(row.s)
        assert cd[k] == row.c
    exchange.cleanup()


def test_range_partitioning_with_sampled_bounds():
    rng = np.random.default_rng(3)
    n = 10000
    t = pa.table({"k": pa.array(rng.integers(0, 10000, n))})
    specs = [(col(0, "k"), False, True)]
    bounds = sample_range_bounds(t, specs, 4, ["k"])
    assert bounds.num_rows == 3
    p = RangePartitioning(specs, 4, bounds)
    cb = ColumnBatch.from_arrow(t)
    ids = p.partition_ids(cb)
    ks = np.asarray(t.column("k"))
    # ranges must be ordered: max of partition p <= min of partition p+1
    for a in range(3):
        if (ids == a).any() and (ids == a + 1).any():
            assert ks[ids == a].max() <= ks[ids == a + 1].min()
    # roughly balanced
    counts = np.bincount(ids, minlength=4)
    assert counts.min() > n // 10


def test_single_partitioning_roundtrip(tmp_path):
    t = pa.table({"a": pa.array([1, 2, 3])})
    scan = MemoryScanExec.from_arrow(t)
    data, index = str(tmp_path / "x.data"), str(tmp_path / "x.index")
    w = ShuffleWriterExec(scan, SinglePartitioning(), data, index)
    list(w.execute(0))
    offsets = read_index_file(index)
    put_resource("t3", [FileSegmentBlock(data, 0, offsets[1])])
    got = IpcReaderExec("t3", S.Schema.from_arrow(t.schema)).execute_collect()
    assert got.to_arrow().column(0).to_pylist() == [1, 2, 3]
