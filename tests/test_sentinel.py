"""Regression sentinel (blaze_tpu/tools/sentinel.py) and the unified
bench-artifact schema (blaze_tpu/tools/bench_schema.py): envelope
fields, direction inference, noise floors, and the CI exit-code
contract the bench trajectory depends on."""

import json
import os

import pytest

from blaze_tpu.tools import sentinel
from blaze_tpu.tools.bench_schema import (BENCH_SCHEMA_VERSION,
                                          ENVELOPE_KEYS, bench_envelope,
                                          write_bench_artifact)


# -- unified bench envelope --------------------------------------------------

def test_envelope_carries_schema_git_and_host():
    env = bench_envelope()
    for k in ENVELOPE_KEYS:
        assert k in env, k
    assert env["schema_version"] == BENCH_SCHEMA_VERSION
    assert env["git_sha"]  # sha or "unknown", never empty
    assert env["host"]["python"]
    assert env["host"]["cpu_count"] >= 1


def test_write_bench_artifact_wraps_and_leg_keys_win(tmp_path):
    path = str(tmp_path / "BENCH_X.json")
    merged = write_bench_artifact(path, {"metric": "m", "value": 7,
                                         "git_sha": "leg-override"})
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk == json.loads(json.dumps(merged, default=str))
    assert on_disk["schema_version"] == BENCH_SCHEMA_VERSION
    assert on_disk["value"] == 7
    assert on_disk["git_sha"] == "leg-override"  # leg keys win


# -- direction inference / flatten -------------------------------------------

@pytest.mark.parametrize("key,want", [
    ("q01.wall_s", "lower"),
    ("serve.p99_latency_ms", "lower"),
    ("spill_bytes", "lower"),
    ("stage_recoveries", "lower"),
    ("e2e.rows_per_sec", "higher"),
    ("tenants.acme.qps", "higher"),
    ("expr_cache_hit_rate", "higher"),
    ("device_utilization", "higher"),
    ("mystery_metric", "unknown"),
])
def test_metric_direction(key, want):
    assert sentinel.metric_direction(key) == want


def test_flatten_skips_envelope_and_bools():
    rec = {"schema_version": 1, "git_sha": "abc", "host": {"cpu_count": 8},
           "value": 2.5, "nested": {"ok": True, "n": 3},
           "list": [1.0, {"x": 4}]}
    flat = sentinel.flatten(rec)
    assert flat == {"value": 2.5, "nested.n": 3.0,
                    "list.0": 1.0, "list.1.x": 4.0}


# -- compare / exit codes ----------------------------------------------------

def _write(tmp_path, name, rec):
    path = str(tmp_path / name)
    write_bench_artifact(path, rec)
    return path


BASE = {"metric": "m", "q01": {"wall_s": 1.0, "rows_per_sec": 1000.0},
        "oddball": 10.0}


def test_identical_artifacts_exit_zero(tmp_path, capsys):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    c = _write(tmp_path, "BENCH_B.json", dict(BASE))
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--ci"]) == 0
    assert "0 regression(s)" in capsys.readouterr().out


def test_regression_exits_two_and_names_metric(tmp_path, capsys):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    worse = {**BASE, "q01": {"wall_s": 1.5, "rows_per_sec": 1000.0}}
    c = _write(tmp_path, "BENCH_B.json", worse)
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10"]) == 2
    out = capsys.readouterr().out
    assert "REGRESSION q01.wall_s" in out
    assert "baseline=1.0 candidate=1.5" in out


def test_improvement_does_not_fail(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    better = {**BASE, "q01": {"wall_s": 0.5, "rows_per_sec": 2000.0}}
    c = _write(tmp_path, "BENCH_B.json", better)
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10"]) == 0


def test_throughput_drop_regresses(tmp_path, capsys):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    worse = {**BASE, "q01": {"wall_s": 1.0, "rows_per_sec": 500.0}}
    c = _write(tmp_path, "BENCH_B.json", worse)
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10"]) == 2
    assert "q01.rows_per_sec" in capsys.readouterr().out


def test_unknown_direction_fails_on_drift_either_way(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    c = _write(tmp_path, "BENCH_B.json", {**BASE, "oddball": 20.0})
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10"]) == 2


def test_change_within_threshold_passes(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    mild = {**BASE, "q01": {"wall_s": 1.05, "rows_per_sec": 1000.0}}
    c = _write(tmp_path, "BENCH_B.json", mild)
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10"]) == 0


def test_abs_floor_suppresses_tiny_changes(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", {"tiny": 1e-9})
    c = _write(tmp_path, "BENCH_B.json", {"tiny": 5e-9})  # +400% but tiny
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10"]) == 0


def test_missing_metric_fails_only_in_ci_mode(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    dropped = {k: v for k, v in BASE.items() if k != "oddball"}
    c = _write(tmp_path, "BENCH_B.json", dropped)
    args = ["--baseline", b, "--candidate", c, "--threshold", "0.10"]
    assert sentinel.main(args) == 0
    assert sentinel.main(args + ["--ci"]) == 2


def test_schema_version_mismatch_fails_in_ci(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    c = _write(tmp_path, "BENCH_B.json",
               {**BASE, "schema_version": BENCH_SCHEMA_VERSION + 1})
    args = ["--baseline", b, "--candidate", c]
    assert sentinel.main(args) == 0  # tolerated outside CI
    assert sentinel.main(args + ["--ci"]) == 2


def test_unloadable_input_exits_one(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    assert sentinel.main(["--baseline", b,
                          "--candidate", str(tmp_path / "nope.json")]) == 1


def test_metrics_filter_limits_the_diff(tmp_path):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    worse = {**BASE, "q01": {"wall_s": 1.5, "rows_per_sec": 1000.0}}
    c = _write(tmp_path, "BENCH_B.json", worse)
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10",
                          "--metrics", "oddball*"]) == 0


def test_directory_mode_merges_by_stem(tmp_path):
    base_dir = tmp_path / "base"
    cand_dir = tmp_path / "cand"
    for d in (base_dir, cand_dir):
        os.makedirs(d)
    _write(base_dir, "BENCH_EXPR.json", {"wall_s": 1.0})
    _write(base_dir, "BENCH_SERVE.json", {"qps": 100.0})
    _write(cand_dir, "BENCH_EXPR.json", {"wall_s": 2.0})  # regressed
    _write(cand_dir, "BENCH_SERVE.json", {"qps": 100.0})
    findings = sentinel.compare(sentinel.load(str(base_dir)),
                                sentinel.load(str(cand_dir)),
                                threshold=0.10)
    regressed = [f["metric"] for f in findings
                 if f["kind"] == "regression"]
    assert regressed == ["EXPR.wall_s"]


def test_json_report_mode(tmp_path, capsys):
    b = _write(tmp_path, "BENCH_A.json", BASE)
    worse = {**BASE, "q01": {"wall_s": 1.5, "rows_per_sec": 1000.0}}
    c = _write(tmp_path, "BENCH_B.json", worse)
    assert sentinel.main(["--baseline", b, "--candidate", c,
                          "--threshold", "0.10", "--json"]) == 2
    report = json.loads(capsys.readouterr().out)
    assert report["regressions"] == 1
    assert report["findings"][0]["metric"] == "q01.wall_s"
    assert report["findings"][0]["direction"] == "lower"
