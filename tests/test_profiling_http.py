"""Profiling HTTP service endpoints: /status, /metrics, /metrics.prom,
/profile/<qid>, /auron, and the /trace/start query-string validation
(the raw text after '?' was previously used verbatim as the trace dir).
"""

import json
import urllib.error
import urllib.request

import pytest

from blaze_tpu.bridge import profiling, ui
from blaze_tpu.memory import MemManager


@pytest.fixture(autouse=True)
def service():
    MemManager.init(4 << 30)
    ui.reset()
    port = profiling.start_http_service()
    yield port
    profiling.stop_http_service()
    ui.reset()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def _get_error(port, path):
    try:
        _get(port, path)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())
    raise AssertionError(f"{path} unexpectedly succeeded")


def test_status_reports_memory_manager(service):
    code, ctype, body = _get(service, "/status")
    assert code == 200
    status = json.loads(body)
    assert "mem_manager" in status
    assert "device_memory" in status


def test_metrics_serves_recorded_trees(service):
    profiling.record_metrics({"name": "FilterExec",
                              "values": {"output_rows": 42},
                              "children": []})
    code, _ctype, body = _get(service, "/metrics")
    assert code == 200
    trees = json.loads(body)
    assert any(t.get("name") == "FilterExec" and
               t["values"]["output_rows"] == 42 for t in trees)


def test_metrics_prom_exposition(service):
    from blaze_tpu.bridge import xla_stats
    import jax.numpy as jnp
    xla_stats.reset()
    f = xla_stats.meter_jit(lambda x: x + 1, name="prom.kernel")
    f(jnp.arange(4))
    f(jnp.arange(4))
    profiling.record_metrics({"name": "ScanExec",
                              "values": {"output_rows": 7,
                                         "io_bytes": 123},
                              "children": []})
    code, ctype, body = _get(service, "/metrics.prom")
    assert code == 200
    assert ctype.startswith("text/plain")
    assert 'blaze_xla_compiles_total{kernel="prom.kernel"} 1' in body
    assert 'blaze_xla_cache_hits_total{kernel="prom.kernel"} 1' in body
    assert "blaze_h2d_bytes_total" in body
    assert "blaze_mem_peak_used_bytes" in body
    assert 'blaze_operator_output_rows_total{operator="ScanExec"} 7' in body
    assert 'blaze_operator_io_bytes_total{operator="ScanExec"} 123' in body
    # HELP/TYPE emitted once per metric family; accumulated *_total
    # families declare themselves counters (they used to claim gauge)
    assert body.count("# TYPE blaze_h2d_bytes_total counter") == 1


def test_profile_endpoints(service):
    profiling.record_profile("q-http-1", {
        "query_id": "q-http-1", "wall_ns": 1000,
        "tree": {"name": "AggExec", "values": {"output_rows": 5},
                 "children": []},
        "output_rows": 5})
    code, _ctype, body = _get(service, "/profile")
    assert code == 200
    listing = json.loads(body)
    assert any(p["query_id"] == "q-http-1" for p in listing)

    code, _ctype, body = _get(service, "/profile/q-http-1")
    assert code == 200
    prof = json.loads(body)
    assert prof["tree"]["name"] == "AggExec"

    code, err = _get_error(service, "/profile/nope")
    assert code == 404
    assert "q-http-1" in err["known"]


def test_profile_ring_evicts_oldest(service):
    for i in range(profiling._MAX_PROFILES + 3):
        profiling.record_profile(f"ring-{i}", {"wall_ns": i})
    known = [p["query_id"] for p in profiling.list_profiles()]
    assert len(known) == profiling._MAX_PROFILES
    assert "ring-0" not in known
    assert f"ring-{profiling._MAX_PROFILES + 2}" in known


def test_query_timeline_endpoint(service):
    from blaze_tpu.bridge import tracing
    tracing.start_tracing()
    try:
        with tracing.execution_context(query="q-http-tl"):
            with tracing.span("task_attempt", task=0, attempt=1,
                              what="http-tl"):
                pass
        code, _ctype, body = _get(service, "/query/q-http-tl/timeline")
        assert code == 200
        tl = json.loads(body)
        assert tl["query_id"] == "q-http-tl"
        assert any(e.get("name") == "task_attempt" and e["ph"] == "X"
                   for e in tl["traceEvents"])
        assert tl["attribution"]["span_count"] >= 1

        code, err = _get_error(service, "/query/never-traced/timeline")
        assert code == 404
        assert "never-traced" in err["error"]
    finally:
        tracing.stop_tracing()
        with tracing._lock:   # stop keeps the buffer; don't leak spans
            tracing._spans.clear()
        tracing.reset_conf_probe()


def test_auron_endpoint(service):
    qid = ui.next_query_id()
    ui.record_conversion(qid, ["FilterExec"], [])
    code, _ctype, body = _get(service, "/auron")
    assert code == 200
    data = json.loads(body)
    assert any(e["query_id"] == qid for e in data["executions"])


def test_trace_start_rejects_unknown_params(service):
    # the old handler took the raw text after '?' as the directory, so
    # '/trace/start?/tmp/x' created a directory literally named that
    code, err = _get_error(service, "/trace/start?/tmp/x")
    assert code == 400
    assert "expected ?dir=" in err["error"]


def test_trace_start_rejects_relative_dir(service):
    code, err = _get_error(service, "/trace/start?dir=relative/path")
    assert code == 400
    assert "absolute" in err["error"]


def test_unknown_path_404_lists_routes(service):
    code, err = _get_error(service, "/nope")
    assert code == 404
    assert "/metrics.prom" in err["paths"]
    assert "/profile/<qid>" in err["paths"]
