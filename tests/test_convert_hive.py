"""Hive glue in the Spark-plan converter (VERDICT r4 missing #6):

  * HiveTableScanExec -> native parquet scan with partition-constant
    columns (NativeHiveTableScanBase.scala:23-105 analog),
  * HiveSimpleUDF/HiveGenericUDF: UDFJson maps to the native
    get_json_object kernel, brickhouse ArrayUnionUDF to array_union
    (NativeConverters.scala:1212-1237), anything else wraps into the
    host-evaluated UDF fallback (HiveUDFUtil.getFunctionClassName)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu.convert.spark import convert_spark_plan
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import create_plan

HIVE_EXEC = "org.apache.spark.sql.hive.execution."
HIVE = "org.apache.spark.sql.hive."
CAT = "org.apache.spark.sql.catalyst.expressions."


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(1 << 30)


def attr(name, dt, eid):
    return [{"class": CAT + "AttributeReference", "num-children": 0,
             "name": name, "dataType": dt, "nullable": True,
             "exprId": {"id": eid, "jvmId": "u"}}]


def lit(value, dt):
    return [{"class": CAT + "Literal", "num-children": 0,
             "value": value, "dataType": dt}]


def _run(ir):
    plan = create_plan(ir)
    out = []
    for p in range(plan.num_partitions):
        out.extend(b.compact().to_arrow() for b in plan.execute(p))
    out = [b for b in out if b.num_rows]
    return (pa.Table.from_batches(out).to_pandas() if out
            else pd.DataFrame())


def _hive_scan(attrs, files, part_fields=None, part_values=None,
               fmt="parquet"):
    node = {"class": HIVE_EXEC + "HiveTableScanExec", "num-children": 0,
            "requestedAttributes": [a for a in attrs],
            "files": files, "format": fmt}
    if part_fields:
        node["partition_schema"] = part_fields
        node["partition_values"] = part_values
    return [node]


def test_hive_table_scan_with_partition_values(tmp_path):
    t = pa.table({"v": pa.array([1.5, 2.5, 3.5])})
    p = str(tmp_path / "part-0.parquet")
    pq.write_table(t, p)
    plan = _hive_scan(
        attr("v", "double", 1) + attr("ds", "string", 2),
        [[p]],
        part_fields=[{"name": "ds", "type": {"id": "utf8"},
                      "nullable": True}],
        part_values=[[["2024-01-01"]]])
    res = convert_spark_plan(plan)
    assert res.plan["kind"] == "parquet_scan"
    assert res.plan["partition_schema"]["fields"][0]["name"] == "ds"
    got = _run(res.plan)
    assert list(got.columns) == ["v", "ds"]
    assert set(got["ds"]) == {"2024-01-01"}
    np.testing.assert_allclose(sorted(got["v"]), [1.5, 2.5, 3.5])


def test_hive_scan_requires_shim_files():
    from blaze_tpu.convert.spark import ConversionError
    plan = [{"class": HIVE_EXEC + "HiveTableScanExec", "num-children": 0,
             "requestedAttributes": [attr("v", "double", 1)[0]]}]
    with pytest.raises(ConversionError, match="files"):
        convert_spark_plan(plan)


def _udfjson_plan(tmp_path, func_wrapper):
    t = pa.table({"j": pa.array(['{"a": {"b": 7}}', "oops"])})
    p = str(tmp_path / "j.parquet")
    pq.write_table(t, p)
    udf = [{"class": HIVE + "HiveSimpleUDF", "num-children": 2,
            "name": "default.get_json_object",
            "funcWrapper": func_wrapper,
            "dataType": "string"}] + attr("j", "string", 1) + \
        lit("$.a.b", "string")
    project = [{"class": "org.apache.spark.sql.execution.ProjectExec",
                "num-children": 1,
                "projectList": [udf]}]
    scan = [{"class": "org.apache.spark.sql.execution.FileSourceScanExec",
             "num-children": 0, "output": [attr("j", "string", 1)[0]],
             "files": [[p]]}]
    return project + scan


def test_hive_udfjson_maps_to_native_get_json_object(tmp_path):
    plan = _udfjson_plan(
        tmp_path,
        "HiveFunctionWrapper(functionClassName="
        "org.apache.hadoop.hive.ql.udf.UDFJson)")
    res = convert_spark_plan(plan)
    assert res.plan is not None
    proj = res.plan["exprs"][0]
    assert proj["kind"] == "scalar_function"
    assert proj["name"] == "get_json_object"
    got = _run(res.plan)
    vals = got.iloc[:, 0]
    assert vals.iloc[0] == "7" and pd.isna(vals.iloc[1])


def test_hive_udfjson_dict_wrapper_form(tmp_path):
    plan = _udfjson_plan(
        tmp_path,
        {"functionClassName": "org.apache.hadoop.hive.ql.udf.UDFJson"})
    res = convert_spark_plan(plan)
    assert res.plan["exprs"][0]["name"] == "get_json_object"


def test_unknown_hive_udf_wraps_as_host_udf(tmp_path):
    plan = _udfjson_plan(
        tmp_path,
        {"functionClassName": "com.example.udf.MyCustomUDF"})
    res = convert_spark_plan(plan)
    assert res.plan is not None
    wrapped = res.plan["exprs"][0]
    assert wrapped["kind"] == "udf"
    assert res.wrapped_udfs and \
        res.wrapped_udfs[0]["class"] == "HiveSimpleUDF"


def test_brickhouse_array_union_behind_conf(tmp_path):
    t = pa.table({"a": pa.array([[1, 2]]), "b": pa.array([[2, 3]])})
    p = str(tmp_path / "ab.parquet")
    pq.write_table(t, p)
    udf = [{"class": HIVE + "HiveGenericUDF", "num-children": 2,
            "name": "brickhouse.array_union",
            "funcWrapper": {"functionClassName":
                            "brickhouse.udf.collect.ArrayUnionUDF"},
            "dataType": {"type": "array", "elementType": "long", "containsNull": True}}] + \
        attr("a", {"type": "array", "elementType": "long", "containsNull": True}, 1) + attr("b", {"type": "array", "elementType": "long", "containsNull": True}, 2)
    project = [{"class": "org.apache.spark.sql.execution.ProjectExec",
                "num-children": 1, "projectList": [udf]}]
    scan = [{"class": "org.apache.spark.sql.execution.FileSourceScanExec",
             "num-children": 0,
             "output": [attr("a", {"type": "array", "elementType": "long", "containsNull": True}, 1)[0],
                        attr("b", {"type": "array", "elementType": "long", "containsNull": True}, 2)[0]],
             "files": [[p]]}]
    with config.scoped(**{"auron.udf.brickhouse.enabled": "true"}):
        res = convert_spark_plan(project + scan)
        assert res.plan["exprs"][0]["kind"] == "scalar_function"
        assert res.plan["exprs"][0]["name"] == "array_union"
        got = _run(res.plan)
    assert list(got.iloc[0, 0]) == [1, 2, 3]


def test_partition_schema_without_values_raises():
    from blaze_tpu.convert.spark import ConversionError
    plan = _hive_scan(
        attr("v", "double", 1) + attr("ds", "string", 2),
        [["/nonexistent.parquet"]],
        part_fields=[{"name": "ds", "type": {"id": "utf8"},
                      "nullable": True}],
        part_values=None)
    # _hive_scan drops empty part_values; build explicitly
    plan[0]["partition_schema"] = [{"name": "ds", "type": {"id": "utf8"},
                                    "nullable": True}]
    plan[0].pop("partition_values", None)
    with pytest.raises(ConversionError, match="partition_values"):
        convert_spark_plan(plan)


def test_partition_values_coerce_metastore_strings(tmp_path):
    """Hive metastore partition values are strings; the converter must
    coerce them against the partition schema (int year here) like
    NativeHiveTableScanBase's Literal cast."""
    t = pa.table({"v": pa.array([1.0, 2.0])})
    p = str(tmp_path / "y.parquet")
    pq.write_table(t, p)
    plan = _hive_scan(
        attr("v", "double", 1) + attr("year", "integer", 2),
        [[p]],
        part_fields=[{"name": "year", "type": {"id": "int32"},
                      "nullable": True}],
        part_values=[[["2024"]]])  # metastore string form
    res = convert_spark_plan(plan)
    assert res.plan["partition_values"] == [[[2024]]]
    got = _run(res.plan)
    assert set(got["year"]) == {2024}


def test_hive_orc_scan_with_partition_values(tmp_path):
    from pyarrow import orc as pa_orc
    t = pa.table({"v": pa.array([10.0, 20.0])})
    p = str(tmp_path / "part.orc")
    pa_orc.write_table(t, p)
    plan = _hive_scan(
        attr("v", "double", 1) + attr("ds", "string", 2),
        [[p]],
        part_fields=[{"name": "ds", "type": {"id": "utf8"},
                      "nullable": True}],
        part_values=[[["2024-02-02"]]], fmt="orc")
    res = convert_spark_plan(plan)
    assert res.plan["kind"] == "orc_scan"
    got = _run(res.plan)
    assert set(got["ds"]) == {"2024-02-02"}
    np.testing.assert_allclose(sorted(got["v"]), [10.0, 20.0])


def test_date_partition_and_hive_null_sentinel(tmp_path):
    """DATE partitions parse 'yyyy-MM-dd' and __HIVE_DEFAULT_PARTITION__
    coerces to NULL (the metastore's null-partition sentinel)."""
    import datetime
    t = pa.table({"v": pa.array([1.0])})
    p1 = str(tmp_path / "a.parquet")
    p2 = str(tmp_path / "b.parquet")
    pq.write_table(t, p1)
    pq.write_table(t, p2)
    plan = _hive_scan(
        attr("v", "double", 1) + attr("dt", "date", 2),
        [[p1, p2]],
        part_fields=[{"name": "dt", "type": {"id": "date32"},
                      "nullable": True}],
        part_values=[[["2024-05-05"], ["__HIVE_DEFAULT_PARTITION__"]]])
    res = convert_spark_plan(plan)
    assert res.plan["partition_values"] == \
        [[[datetime.date(2024, 5, 5)], [None]]]
    got = _run(res.plan)
    vals = set(got["dt"].astype(object))
    assert datetime.date(2024, 5, 5) in vals
    assert any(pd.isna(v) for v in got["dt"])


def test_malformed_partition_value_raises_conversion_error():
    from blaze_tpu.convert.spark import ConversionError
    plan = _hive_scan(
        attr("v", "double", 1) + attr("year", "integer", 2),
        [["/x.parquet"]],
        part_fields=[{"name": "year", "type": {"id": "int32"},
                      "nullable": True}],
        part_values=[[["not-a-year"]]])
    with pytest.raises(ConversionError, match="does not coerce"):
        convert_spark_plan(plan)
