"""Scalar-function registry tests — per-function Spark-semantics cases,
modeled on the reference's ~150 #[test]s across datafusion-ext-functions
(e.g. spark_dates.rs has 31, SURVEY.md §4)."""

import datetime

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import schema as S
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import col, lit
from blaze_tpu.funcs import fn, registered_names


def make_batch(**cols):
    arrays, fields = [], []
    for name, spec in cols.items():
        arr = spec if isinstance(spec, pa.Array) else pa.array(spec)
        fields.append(pa.field(name, arr.type))
        arrays.append(arr)
    return ColumnBatch.from_arrow(
        pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields)))


def ev(batch, expr):
    return expr.evaluate(batch).to_host(batch.num_rows).to_pylist()


def test_registry_breadth():
    # the reference registers ~40 ext functions + builtins; require >= 70
    assert len(registered_names()) >= 70


def test_math_basics():
    b = make_batch(x=[4.0, 9.0, None])
    assert ev(b, fn("sqrt", col(0))) == [2.0, 3.0, None]
    assert ev(b, fn("abs", fn("negative", col(0)))) == [4.0, 9.0, None]
    c = make_batch(x=[1.5, -1.5, 2.5])
    assert ev(c, fn("ceil", col(0))) == [2, -1, 3]
    assert ev(c, fn("floor", col(0))) == [1, -2, 2]


def test_round_half_up_vs_bround_half_even():
    b = make_batch(x=[2.5, 3.5, -2.5])
    assert ev(b, fn("round", col(0))) == [3.0, 4.0, -3.0]
    assert ev(b, fn("bround", col(0))) == [2.0, 4.0, -2.0]
    c = make_batch(x=[1.245])
    assert ev(c, fn("round", col(0), lit(2)))[0] == pytest.approx(1.25)


def test_greatest_least_skip_nulls():
    b = make_batch(x=[1, None, 5], y=[3, 2, None])
    assert ev(b, fn("greatest", col(0), col(1))) == [3, 2, 5]
    assert ev(b, fn("least", col(0), col(1))) == [1, 2, 5]


DATES = pa.array([datetime.date(2023, 5, 17), datetime.date(2020, 2, 29),
                  datetime.date(1969, 12, 31), None])


def test_date_fields():
    b = make_batch(d=DATES)
    assert ev(b, fn("year", col(0))) == [2023, 2020, 1969, None]
    assert ev(b, fn("month", col(0))) == [5, 2, 12, None]
    assert ev(b, fn("day", col(0))) == [17, 29, 31, None]
    assert ev(b, fn("quarter", col(0))) == [2, 1, 4, None]
    assert ev(b, fn("dayofweek", col(0))) == [4, 7, 4, None]  # Wed,Sat,Wed
    assert ev(b, fn("dayofyear", col(0))) == [137, 60, 365, None]


def test_date_arith():
    b = make_batch(d=DATES)
    assert ev(b, fn("date_add", col(0), lit(10)))[0] == datetime.date(2023, 5, 27)
    assert ev(b, fn("date_sub", col(0), lit(1)))[1] == datetime.date(2020, 2, 28)
    assert ev(b, fn("last_day", col(0)))[:2] == [datetime.date(2023, 5, 31),
                                                 datetime.date(2020, 2, 29)]
    assert ev(b, fn("add_months", col(0), lit(1)))[1] == datetime.date(2020, 3, 29)
    # end-of-month clamp: Jan 31 + 1 month = Feb 29 (2020 leap)
    c = make_batch(d=pa.array([datetime.date(2020, 1, 31)]))
    assert ev(c, fn("add_months", col(0), lit(1))) == [datetime.date(2020, 2, 29)]
    b2 = make_batch(a=pa.array([datetime.date(2023, 5, 17)]),
                    b=pa.array([datetime.date(2023, 5, 10)]))
    assert ev(b2, fn("datediff", col(0), col(1))) == [7]


def test_trunc_and_weekofyear():
    b = make_batch(d=pa.array([datetime.date(2023, 5, 17)]))
    assert ev(b, fn("trunc", col(0), lit("year"))) == [datetime.date(2023, 1, 1)]
    assert ev(b, fn("trunc", col(0), lit("month"))) == [datetime.date(2023, 5, 1)]
    assert ev(b, fn("trunc", col(0), lit("week"))) == [datetime.date(2023, 5, 15)]
    assert ev(b, fn("weekofyear", col(0))) == [20]
    # ISO edge: 2021-01-01 is week 53 of 2020
    c = make_batch(d=pa.array([datetime.date(2021, 1, 1)]))
    assert ev(c, fn("weekofyear", col(0))) == [53]


def test_timestamp_fields_and_trunc():
    ts = pa.array([datetime.datetime(2023, 5, 17, 13, 45, 59)],
                  type=pa.timestamp("us"))
    b = make_batch(t=ts)
    assert ev(b, fn("hour", col(0))) == [13]
    assert ev(b, fn("minute", col(0))) == [45]
    assert ev(b, fn("second", col(0))) == [59]
    got = ev(b, fn("date_trunc", lit("hour"), col(0)))
    assert got == [datetime.datetime(2023, 5, 17, 13, 0, 0)]


def test_string_functions():
    b = make_batch(s=["Hello", "wORld", None])
    assert ev(b, fn("upper", col(0))) == ["HELLO", "WORLD", None]
    assert ev(b, fn("lower", col(0))) == ["hello", "world", None]
    assert ev(b, fn("length", col(0))) == [5, 5, None]
    assert ev(b, fn("reverse", col(0))) == ["olleH", "dlROw", None]
    assert ev(b, fn("initcap", col(0))) == ["Hello", "World", None]
    b2 = make_batch(s=["a,b,c"])
    assert ev(b2, fn("split", col(0), lit(","))) == [["a", "b", "c"]]
    assert ev(b2, fn("replace", col(0), lit(","), lit("-"))) == ["a-b-c"]


def test_concat_ws_skips_nulls():
    b = make_batch(x=["a", None], y=[None, "b"], z=["c", "d"])
    got = ev(b, fn("concat_ws", lit("/"), col(0), col(1), col(2)))
    assert got == ["a/c", "b/d"]


def test_substring_lpad_rpad():
    b = make_batch(s=["hello"])
    assert ev(b, fn("substring", col(0), lit(2), lit(3))) == ["ell"]
    assert ev(b, fn("substring", col(0), lit(-3), lit(2))) == ["ll"]
    assert ev(b, fn("lpad", col(0), lit(8), lit("*"))) == ["***hello"]
    assert ev(b, fn("rpad", col(0), lit(3))) == ["hel"]
    assert ev(b, fn("substring_index", col(0), lit("l"), lit(1))) == ["he"]
    assert ev(b, fn("substring_index", col(0), lit("l"), lit(-1))) == ["o"]


def test_instr_1_based():
    b = make_batch(s=["hello", "world", None])
    assert ev(b, fn("instr", col(0), lit("l"))) == [3, 4, None]
    assert ev(b, fn("instr", col(0), lit("z"))) == [0, 0, None]


def test_crypto():
    b = make_batch(s=["abc"])
    assert ev(b, fn("md5", col(0))) == ["900150983cd24fb0d6963f7d28e17f72"]
    assert ev(b, fn("sha2", col(0), lit(256))) == [
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"]
    assert ev(b, fn("crc32", col(0))) == [891568578]


def test_hash_matches_kernel():
    """hash()/xxhash64() expression == shuffle hash kernels (bit-exact)."""
    import jax.numpy as jnp
    from blaze_tpu.kernels import hashing as H
    b = make_batch(x=pa.array([1, 2, 3], type=pa.int64()))
    got = ev(b, fn("hash", col(0)))
    want = H.hash_columns([(np.array([1, 2, 3], dtype=np.int64), None,
                            "int64")], seed=42, xp=np, algo="murmur3")
    assert got == [int(x) for x in want]


def test_get_json_object():
    b = make_batch(j=['{"a": {"b": 2}, "xs": [1, 2, 3]}', "oops", None])
    assert ev(b, fn("get_json_object", col(0), lit("$.a.b"))) == ["2", None, None]
    assert ev(b, fn("get_json_object", col(0), lit("$.xs[1]"))) == ["2", None, None]
    assert ev(b, fn("get_json_object", col(0), lit("$.a"))) == \
        ['{"b": 2}', None, None]
    assert ev(b, fn("get_json_object", col(0), lit("$.xs[*]"))) == \
        ["[1, 2, 3]", None, None]
    assert ev(b, fn("get_json_object", col(0), lit("$.zzz"))) == [None, None, None]


def test_arrays_and_maps():
    b = make_batch(x=[1, 4], y=[2, 5], z=[3, 6])
    assert ev(b, fn("make_array", col(0), col(1), col(2))) == [[1, 2, 3],
                                                               [4, 5, 6]]
    lb = make_batch(xs=pa.array([[1, 2, 2], None], type=pa.list_(pa.int64())))
    assert ev(lb, fn("array_distinct", col(0))) == [[1, 2], None]
    assert ev(lb, fn("size", col(0))) == [3, -1]
    assert ev(lb, fn("array_max", col(0))) == [2, None]
    mb = make_batch(s=["a:1,b:2,a:3"])
    assert ev(mb, fn("str_to_map", col(0))) == [[("a", "3"), ("b", "2")]]
    kb = make_batch(m=pa.array([[("k1", 10), ("k2", 20)]],
                               type=pa.map_(pa.utf8(), pa.int64())))
    assert ev(kb, fn("map_keys", col(0))) == [["k1", "k2"]]
    assert ev(kb, fn("element_at", col(0), lit("k2"))) == [20]


def test_decimal_helpers():
    dec = pa.array([None], type=pa.decimal128(10, 2)).fill_null(0)
    b = make_batch(d=pa.array([1550, -99], type=pa.int64()))
    got = ev(b, fn("make_decimal", col(0), out_type=S.decimal(10, 2)))
    import decimal as pydec
    assert got == [pydec.Decimal("15.50"), pydec.Decimal("-0.99")]


def test_string_column_valued_args():
    # per-row (non-literal) position/width/count arguments (ADVICE r1)
    b = make_batch(s=["hello", "hello", "hello"], p=[1, 2, 3], w=[6, 7, 2])
    assert ev(b, fn("substring", col(0), col(1), lit(3))) == \
        ["hel", "ell", "llo"]
    assert ev(b, fn("lpad", col(0), col(2), lit("*"))) == \
        ["*hello", "**hello", "he"]
    assert ev(b, fn("rpad", col(0), col(2), lit("*"))) == \
        ["hello*", "hello**", "he"]
    assert ev(b, fn("repeat", col(0), col(1))) == \
        ["hello", "hellohello", "hellohellohello"]
    b2 = make_batch(s=["hello", "world"], n=["l", "ld"])
    assert ev(b2, fn("instr", col(0), col(1))) == [3, 4]
    b3 = make_batch(s=["a,b;c", "a,b;c"], d=[",", ";"], c=[1, -1])
    assert ev(b3, fn("substring_index", col(0), col(1), col(2))) == \
        ["a", "c"]
    # pc-kernel functions reject column-valued pattern args instead of
    # silently applying row 0's value
    b4 = make_batch(s=["ab", "cd"], pat=["a", "c"])
    with pytest.raises(NotImplementedError):
        ev(b4, fn("replace", col(0), col(1), lit("-")))


def test_concat_ws_null_separator():
    b = make_batch(sep=["/", None], x=["a", "a"], y=["b", "b"])
    assert ev(b, fn("concat_ws", col(0), col(1), col(2))) == ["a/b", None]


def test_string_null_args_propagate():
    # NULL length / needle / fill -> NULL result (code-review r2)
    b = make_batch(s=["hello"], nl=pa.array([None], type=pa.int64()))
    assert ev(b, fn("substring", col(0), lit(1), col(1))) == [None]
    assert ev(b, fn("instr", col(0), lit(None))) == [None]
    b2 = make_batch(s=["hello"], w=[-1])
    assert ev(b2, fn("lpad", col(0), col(1), lit("*"))) == [""]
    assert ev(b2, fn("rpad", col(0), col(1), lit("*"))) == [""]
    b3 = make_batch(s=["hello"], f=pa.array([None], type=pa.string()))
    assert ev(b3, fn("lpad", col(0), lit(8), col(1))) == [None]


def test_array_column_valued_args():
    b = make_batch(a=pa.array([[1, 2], [1, 2]]), n=[1, 3])
    assert ev(b, fn("array_contains", col(0), col(1))) == [True, False]
    b2 = make_batch(a=pa.array([["x", "y"], ["x", "y"]]), s=["-", "+"])
    assert ev(b2, fn("array_join", col(0), col(1))) == ["x-y", "x+y"]


def test_string_null_literal_pattern_args():
    # NULL literal pattern/delim args -> NULL results (code-review r2)
    b = make_batch(s=["a,b"])
    assert ev(b, fn("split", col(0), lit(None))) == [None]
    assert ev(b, fn("replace", col(0), lit(None), lit("-"))) == [None]
    assert ev(b, fn("trim", col(0), lit(None))) == [None]
    assert ev(b, fn("translate", col(0), lit(None), lit("x"))) == [None]
    b2 = make_batch(s=["a:1,b:2"])
    assert ev(b2, fn("str_to_map", col(0), lit(None), lit(":"))) == [None]
    b3 = make_batch(a=pa.array([[1, 2]]),
                    n=pa.array([None], type=pa.int64()))
    assert ev(b3, fn("array_contains", col(0), col(1))) == [None]


def test_split_limit_semantics():
    # Java Pattern.split limits (code-review r2): limit=1 -> whole string,
    # limit=0 -> drop trailing empties, NULL limit -> NULL
    b = make_batch(s=["a,b,c"])
    assert ev(b, fn("split", col(0), lit(","), lit(1))) == [["a,b,c"]]
    assert ev(b, fn("split", col(0), lit(","), lit(2))) == [["a", "b,c"]]
    assert ev(b, fn("split", col(0), lit(","), lit(None))) == [None]
    b2 = make_batch(s=["a,b,,"])
    assert ev(b2, fn("split", col(0), lit(","), lit(0))) == [["a", "b"]]
    assert ev(b2, fn("split", col(0), lit(","), lit(-1))) == [["a", "b", "", ""]]
