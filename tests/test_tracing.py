"""Distributed tracing, flight recorder, and per-query attribution.

Covers the tentpole of the observability PR: the conf-lazy enable knob
(zero hot-path cost when off), cross-process trace stitching over the
worker wire protocol (clock rebase, parent span linkage, worker tags),
speculation winner/loser linking, retry/backoff spans, streaming epoch
spans and recovery instants, the crash flight recorder (deadline,
quota-kill, stream-recovery-exhausted classifications, first-fatal
wins), the Chrome-trace timeline endpoint payload, per-query resource
attribution, and the profile-store LRU cap satellite.
"""

import json
import os
import threading
import time

import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import context as bridge_context
from blaze_tpu.bridge import profiling, tracing, xla_stats
from blaze_tpu.bridge.context import TaskKilledError, current_attempt_token
from blaze_tpu.bridge.tasks import run_tasks
from blaze_tpu.memory import MemManager
from blaze_tpu.ops.kafka import KafkaRecord
from blaze_tpu.ops.window import EventTimeWindowSpec
from blaze_tpu.streaming import (MemoryStreamSource, StreamExecutor,
                                 StreamWindowConfig)

ECHO = "blaze_tpu.parallel.workers:_task_echo"
SLEEP = "blaze_tpu.parallel.workers:_task_sleep"

_KEYS = (config.TRACE_ENABLE, config.FLIGHT_RECORDER_ENABLE,
         config.FLIGHT_RECORDER_DIR, config.FLIGHT_RECORDER_SPANS,
         config.PROFILE_STORE_MAX,
         config.WORKERS_ENABLE, config.WORKERS_COUNT,
         config.WORKERS_HEARTBEAT_MS, config.WORKERS_RESTART_BACKOFF_MS,
         config.SPECULATION_ENABLE, config.SPECULATION_QUANTILE,
         config.SPECULATION_MULTIPLIER, config.SPECULATION_MIN_MS,
         config.TASK_RETRY_BACKOFF_MS, config.TASK_MAX_ATTEMPTS,
         config.STREAM_MAX_RECOVERIES)


def _drop_buffered_spans():
    # stop_tracing() deliberately KEEPS the buffer (the /trace/stop
    # contract); tests need a truly empty tracer, so drain it too.
    tracing.stop_tracing()
    with tracing._lock:
        tracing._spans.clear()


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    bridge_context.reset_flight_recorder()
    _drop_buffered_spans()
    tracing.reset_conf_probe()
    try:
        yield
    finally:
        from blaze_tpu.parallel import workers
        workers.shutdown_pool(wait=False)
        for opt in _KEYS:
            config.conf.unset(opt.key)
        faults.clear()
        bridge_context.reset_flight_recorder()
        _drop_buffered_spans()
        tracing.reset_conf_probe()
        MemManager.init(4 << 30)


def _names(records):
    return [r["name"] for r in records]


def _by_name(records, name):
    return [r for r in records if r["name"] == name]


# -- enable knob ------------------------------------------------------------

def test_tracing_default_off_and_wire_context_absent():
    """Default-off contract: no spans buffered, and wire_context() is
    None so the worker task message grows by NOTHING on the off path."""
    assert not tracing.enabled()
    with tracing.span("task", task=0):
        pass
    tracing.instant("task_retry", task=0)
    assert tracing.wire_context(worker=1) is None
    assert tracing.spans() == []


def test_conf_knob_enables_lazily_and_unset_disables():
    config.conf.set(config.TRACE_ENABLE.key, "on")
    tracing.reset_conf_probe()  # forget the probe: next emit re-reads conf
    with tracing.span("task", task=7):
        pass
    assert tracing.enabled()
    got = _by_name(tracing.spans(), "task")
    assert got and got[-1]["attrs"]["task"] == 7
    config.conf.unset(config.TRACE_ENABLE.key)
    tracing.reset_conf_probe()
    with tracing.span("task", task=8):
        pass
    assert not tracing.enabled()
    assert tracing.wire_context() is None


def test_unknown_span_name_rejected_when_enabled():
    tracing.start_tracing()
    with pytest.raises(ValueError, match="unregistered span"):
        with tracing.span("not-a-registered-span"):
            pass
    with pytest.raises(ValueError, match="unregistered span"):
        tracing.instant("also-not-registered")
    # wildcard names pass: operator spans are per-operator dynamic
    with tracing.span("operator:hash_agg", rows=1):
        pass
    assert _by_name(tracing.spans(), "operator:hash_agg")


# -- wire roundtrip ---------------------------------------------------------

def test_wire_context_and_child_rebase_stitch_one_trace():
    """Parent packs a compact context; the child-side scope buffers spans
    on a skewed clock; ingest() rebases them onto the parent clock and
    parents them under the dispatching span."""
    tracing.start_tracing()
    with tracing.execution_context(query="q-wire", stage="s0"):
        with tracing.span("task_attempt", task=3, attempt=0,
                          what="wire-test"):
            wctx = tracing.wire_context(worker=5)
    assert wctx is not None
    assert wctx["query"] == "q-wire" and wctx["stage"] == "s0"
    assert wctx["worker"] == 5
    parent_sid = wctx["parent"]
    assert parent_sid == _by_name(tracing.spans(), "task_attempt")[0]["sid"]

    # child side: adopt the wire context; spans go to the child buffer
    with tracing.remote_task_scope(wctx):
        with tracing.span("worker_task", pid=123, fn=ECHO):
            time.sleep(0.01)
        tracing.instant("worker_heartbeat", pid=123)
    shipped = tracing.take_buffered()
    assert sorted(_names(shipped)) == ["worker_heartbeat", "worker_task"]
    assert all(r["ctx"]["query"] == "q-wire" for r in shipped)
    wt = _by_name(shipped, "worker_task")[0]
    assert wt["parent"] == parent_sid

    # simulate a child whose perf_counter origin is 5s behind ours
    skew_ns = 5_000_000_000
    for r in shipped:
        r["t0_ns"] -= skew_ns
        r["t1_ns"] -= skew_ns
    before = time.perf_counter_ns()
    n = tracing.ingest(shipped, worker=5,
                       clock_ns=time.perf_counter_ns() - skew_ns)
    assert n == 2
    stitched = _by_name(tracing.spans_for_query("q-wire"), "worker_task")
    assert stitched and stitched[0]["worker"] == 5
    # rebased back onto our clock: within transit slop of `before`
    assert abs(stitched[0]["t1_ns"] - before) < 1_000_000_000


def test_worker_pool_stitches_child_spans_into_one_query_trace():
    """End to end over the real wire: process-isolated worker tasks ship
    their spans home in heartbeat/result frames; the parent trace holds
    ONE query with task_attempt -> worker_task parent links and
    worker-tagged heartbeat instants."""
    config.conf.set(config.WORKERS_ENABLE.key, "true")
    config.conf.set(config.WORKERS_COUNT.key, 1)
    config.conf.set(config.WORKERS_HEARTBEAT_MS.key, 30)
    from blaze_tpu.parallel import workers
    pool = workers.get_pool()
    assert pool is not None
    pool.run({"fn": ECHO, "args": ("warm",)}, timeout_s=60.0)

    tracing.start_tracing()
    before = xla_stats.snapshot()
    with tracing.execution_context(query="q-pool"):
        out = run_tasks(lambda i: None, 2, 30.0, "pool-trace-wave",
                        max_workers=2,
                        remote=lambda i: {"fn": SLEEP, "args": (0.25, i)})
    assert [r["value"] for r in out] == [0, 1]
    recs = tracing.spans_for_query("q-pool")
    attempts = _by_name(recs, "task_attempt")
    wtasks = _by_name(recs, "worker_task")
    assert len(attempts) == 2 and len(wtasks) == 2
    attempt_sids = {r["sid"] for r in attempts}
    # every child span is stitched under its dispatching attempt and
    # tagged with the worker slot that ran it
    assert all(r.get("parent") in attempt_sids for r in wtasks)
    assert all("worker" in r for r in wtasks)
    assert all(r["ctx"]["query"] == "q-pool" for r in wtasks)
    # 0.25s of child work at 30ms heartbeats: liveness beats streamed
    beats = _by_name(tracing.spans(), "worker_heartbeat")
    assert beats and all("worker" in r for r in beats)
    assert xla_stats.delta(before).get("obs_spans_ingested", 0) >= 2


# -- speculation and retries ------------------------------------------------

def test_speculation_attempts_link_winner_and_loser():
    config.conf.set(config.SPECULATION_ENABLE.key, "on")
    config.conf.set(config.SPECULATION_QUANTILE.key, 0.25)
    config.conf.set(config.SPECULATION_MULTIPLIER.key, 1.0)
    config.conf.set(config.SPECULATION_MIN_MS.key, 10)
    tracing.start_tracing()
    lock = threading.Lock()
    calls = {}

    def fn(i):
        with lock:
            attempt = calls[i] = calls.get(i, -1) + 1
        if i == 3 and attempt == 0:
            tok = current_attempt_token()
            if not tok.wait(8.0):
                raise AssertionError("straggler was never cancelled")
            raise TaskKilledError("cooperative cancel observed")
        return i

    with tracing.execution_context(query="q-spec"):
        out = run_tasks(fn, 4, 10.0, "spec trace wave", max_workers=4)
    assert out == [0, 1, 2, 3]
    recs = tracing.spans_for_query("q-spec")
    launched = _by_name(recs, "speculation_attempt")
    wins = _by_name(recs, "speculation_win")
    losers = _by_name(recs, "speculation_loser")
    assert launched and wins and losers
    win = wins[0]["attrs"]
    assert win["task"] == 3
    # the winner names its losers and each loser points back at the
    # winner: one linked hedge pair on the query's own trace
    assert losers[0]["attrs"]["attempt"] in win["loser_attempts"]
    assert losers[0]["attrs"]["winner_attempt"] == win["winner_attempt"]
    spec_attempts = [r for r in _by_name(recs, "task_attempt")
                     if r["attrs"].get("speculative")]
    assert spec_attempts, "the hedged duplicate must carry speculative=True"


def test_retry_emits_instant_and_backoff_wait_span():
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 20)
    tracing.start_tracing()
    with faults.scoped(("task-start", dict(at=(1,))), seed=5):
        with tracing.execution_context(query="q-retry"):
            out = run_tasks(lambda i: i + 100, 1, 30.0, "retry trace",
                            max_workers=1)
    assert out == [100]
    recs = tracing.spans_for_query("q-retry")
    retries = _by_name(recs, "task_retry")
    waits = _by_name(recs, "backoff_wait")
    attempts = _by_name(recs, "task_attempt")
    assert retries and waits
    assert retries[0]["attrs"]["attempt"] == 1
    assert retries[0]["attrs"]["error"] == "InjectedFault"
    assert waits[0]["dur_ns"] >= 10_000_000  # the sleep really happened
    # the task-start fault fires BEFORE the attempt span opens, so only
    # the successful retry attempt has a task_attempt span
    assert [r["attrs"]["attempt"] for r in attempts] == [2]
    assert _by_name(recs, "fault_injected")


# -- streaming --------------------------------------------------------------

_SCHEMA = {"fields": [
    {"name": "k", "type": {"id": "utf8"}, "nullable": True},
    {"name": "v", "type": {"id": "int64"}, "nullable": True}]}

_WIN = StreamWindowConfig(spec=EventTimeWindowSpec(size_ms=1000),
                          keys=["k"], aggs=[("sum", "v"), ("count", None)])


def _stream_plan():
    return {"kind": "kafka_scan", "topic": "orders", "format": "json",
            "operator_id": "trace-stream", "num_partitions": 1,
            "schema": _SCHEMA}


def _stream_records(n):
    return [KafkaRecord(value=json.dumps({"k": f"k{i % 2}",
                                          "v": i}).encode("utf-8"),
                        offset=i, partition=0, timestamp_ms=i * 100)
            for i in range(n)]


def _stream_exec(tmp_path, tag="a"):
    return StreamExecutor(_stream_plan(),
                          MemoryStreamSource([_stream_records(24)]), _WIN,
                          sink_dir=str(tmp_path / f"sink-{tag}"),
                          checkpoint_dir=str(tmp_path / f"ckpt-{tag}"),
                          max_records_per_poll=6)


def test_stream_epochs_become_spans_and_recovery_an_instant(tmp_path):
    tracing.start_tracing()
    ex = _stream_exec(tmp_path)
    with faults.scoped(("stream-epoch", dict(at=(2,))), seed=9):
        summary = ex.run()
    assert summary["recoveries"] == 1
    epochs = _by_name(tracing.spans(), "stream_epoch")
    assert len(epochs) >= summary["epochs"]
    assert {r["attrs"]["epoch"] for r in epochs} >= set(
        range(summary["epochs"]))
    rec = _by_name(tracing.spans(), "stream_recovery")
    assert rec and rec[0]["attrs"]["resume_epoch"] >= 0


def test_stream_recovery_exhaustion_dumps_flight_record(tmp_path):
    config.conf.set(config.FLIGHT_RECORDER_DIR.key, str(tmp_path / "fd"))
    config.conf.set(config.STREAM_MAX_RECOVERIES.key, 0)
    tracing.start_tracing()
    ex = _stream_exec(tmp_path, tag="x")
    with faults.scoped(("stream-epoch", dict(at=(1,))), seed=2):
        with pytest.raises(faults.InjectedFault):
            ex.run()
    dumps = bridge_context.flight_dumps()
    assert len(dumps) == 1
    qid, path = next(iter(dumps.items()))
    rec = bridge_context.flight_dump(qid)
    assert rec["classification"] == "stream-recovery-exhausted"
    assert "recovery exhausted" in rec["reason"]
    assert path and os.path.exists(path)


# -- flight recorder --------------------------------------------------------

def _service_fatal(tmp_path, executor, **submit_kw):
    from blaze_tpu.serving.service import QueryService
    config.conf.set(config.FLIGHT_RECORDER_DIR.key, str(tmp_path / "fd"))
    svc = QueryService(max_concurrent=1, executor=executor)
    try:
        h = svc.submit({"kind": "noop"}, query_id="q-fatal", **submit_kw)
        with pytest.raises(Exception):
            h.result(10)
        return h
    finally:
        svc.shutdown()


def test_deadline_fatal_dumps_flight_record(tmp_path):
    tracing.start_tracing()

    def ex(plan, ctx, handle):
        time.sleep(0.2)
        ctx.check()

    _service_fatal(tmp_path, ex, deadline_ms=50)
    rec = bridge_context.flight_dump("q-fatal")
    assert rec is not None
    assert rec["classification"] == "deadline"
    assert rec["query_id"] == "q-fatal"
    # the dump is a self-contained post-mortem: recent spans, counter
    # deltas since query start, and the live config snapshot
    blob = json.load(open(rec["path"]))
    assert blob["classification"] == "deadline"
    assert "spans" in blob and "counters" in blob and "config" in blob
    assert any(s["name"] == "admission_wait" for s in blob["spans"])
    assert _by_name(tracing.spans(), "flight_dump")


def test_quota_kill_fatal_dumps_and_first_fatal_wins(tmp_path):
    def ex(plan, ctx, handle):
        ctx.cancel(reason="scan exceeded quota", kind="mem")
        ctx.check()

    _service_fatal(tmp_path, ex)
    rec = bridge_context.flight_dump("q-fatal")
    assert rec is not None and rec["classification"] == "quota-kill"
    # first-fatal-wins: a later classification cannot overwrite the dump
    assert bridge_context.record_fatal("q-fatal", "again", "deadline") is None
    assert bridge_context.flight_dump("q-fatal")["classification"] \
        == "quota-kill"


def test_flight_recorder_disabled_by_knob(tmp_path):
    config.conf.set(config.FLIGHT_RECORDER_ENABLE.key, "false")

    def ex(plan, ctx, handle):
        ctx.cancel(kind="mem")
        ctx.check()

    _service_fatal(tmp_path, ex)
    assert bridge_context.flight_dump("q-fatal") is None


# -- timeline + attribution -------------------------------------------------

def test_query_timeline_is_perfetto_loadable_with_attribution():
    tracing.start_tracing()
    with tracing.execution_context(query="q-tl"):
        with tracing.span("task_attempt", task=0, attempt=1,
                          what="tl-test"):
            time.sleep(0.005)
        tracing.emit_span("stream_epoch", 2_000_000, epoch=0, query="q-tl")
        tracing.instant("mem_spill", bytes=4096, consumer="agg",
                        cause="query-quota")
        tracing.instant("xla_compile", kernel="tl.kernel")
    wt = {"name": "worker_task", "t0_ns": 1, "t1_ns": 2_000_001,
          "dur_ns": 2_000_000, "sid": 999_999, "thread": 1,
          "ctx": {"query": "q-tl"}, "attrs": {}}
    tracing.ingest([wt], worker=3)

    tl = profiling.query_timeline("q-tl")
    assert tl["query_id"] == "q-tl"
    events = tl["traceEvents"]
    json.dumps(tl)  # the payload must be directly Perfetto-loadable
    assert all({"name", "ph", "pid", "tid"} <= set(e) for e in events)
    durs = [e for e in events if e["ph"] == "X"]
    assert durs and all("dur" in e and "ts" in e for e in durs)
    assert any(e["ph"] == "i" for e in events)
    meta = [e for e in events if e["ph"] == "M"]
    # track routing: worker spans land on their own worker process,
    # epochs and device dispatches on dedicated driver-side tracks
    procs = {e["args"]["name"] for e in meta if e["name"] == "process_name"}
    assert {"driver", "worker-3"} <= procs
    threads = {e["args"]["name"] for e in meta if e["name"] == "thread_name"}
    assert {"epoch-0", "device"} <= threads

    attr = tl["attribution"]
    assert attr["task_cpu_seconds"] >= 0.005
    assert attr["worker_task_seconds"] == pytest.approx(0.002)
    assert attr["spill_bytes"] == 4096
    assert attr["span_count"] == len(tracing.spans_for_query("q-tl"))
    assert set(attr["shuffle_bytes_by_tier"]) == {"device", "file", "rss"}


def test_query_timeline_unknown_query_is_none():
    assert profiling.query_timeline("never-ran") is None


# -- satellites: profile store LRU, registry pin ----------------------------

def test_profile_store_lru_cap_counts_evictions():
    config.conf.set(config.PROFILE_STORE_MAX.key, 3)
    before = xla_stats.snapshot()
    for i in range(5):
        profiling.record_profile(f"lru-{i}", {"wall_ns": 100})
    kept = [p["query_id"] for p in profiling.list_profiles()]
    # the cap bounds the WHOLE store: exactly the 3 newest survive
    assert len(kept) == 3
    assert kept[-3:] == ["lru-2", "lru-3", "lru-4"]
    assert xla_stats.delta(before).get("obs_profile_evictions", 0) >= 2
    # get_profile is an LRU touch: re-reading the oldest survivor
    # protects it from the next eviction
    assert profiling.get_profile("lru-2") is not None
    profiling.record_profile("lru-5", {"wall_ns": 100})
    kept = [p["query_id"] for p in profiling.list_profiles()]
    assert "lru-2" in kept and "lru-3" not in kept


def test_span_registry_pin():
    """The full span vocabulary, pinned: adding a span name means
    registering it AND updating docs/observability.md AND exercising it
    in a test (test_span_names.py enforces the latter two)."""
    assert set(tracing.SPAN_NAMES) == {
        "task", "task_attempt", "backoff_wait", "admission_wait",
        "worker_task", "device_exchange", "rss_exchange",
        "shuffle_exchange", "stage_recovery", "stage_loop_chunk",
        "stream_epoch", "explain_analyze", "operator:*",
        "task_retry", "fault_injected", "xla_compile",
        "device_shuffle_fallback", "rss_shuffle_fallback",
        "stage_loop_fallback", "quota_breach", "mem_spill",
        "worker_heartbeat", "worker_cancel_escalation",
        "speculation_attempt", "speculation_win", "speculation_loser",
        "stream_recovery", "flight_dump",
        "aqe_rewrite", "aqe_history_seed",
        "result_cache_hit", "subplan_cache_hit",
        "fleet_replica_down", "fleet_replica_up",
    }
    assert all(doc.strip() for doc in tracing.SPAN_NAMES.values())
