"""Core operator tests: scan/filter/project/limit/union/expand/sort.

Modeled on the reference's pure-native operator tests with TestMemoryExec
inputs (SURVEY.md §4 tier 1; e.g. sort_exec.rs fuzz + merge tests).
"""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu import schema as S
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import (ExpandExec, FilterExec, FilterProjectExec,
                           LimitExec, MemoryScanExec, ParquetScanExec,
                           ProjectExec, RenameColumnsExec, SortExec,
                           UnionExec)


def table(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return pa.table({
        "a": pa.array(rng.integers(0, 50, n)),
        "b": pa.array(rng.random(n) * 100),
        "s": pa.array([f"id_{i % 7}" for i in range(n)]),
    })


def test_memory_scan_partitions():
    t = table(1000)
    scan = MemoryScanExec.from_arrow(t, num_partitions=3, batch_rows=100)
    total = sum(b.selected_count() for p in range(3) for b in scan.execute(p))
    assert total == 1000


def test_filter_project_pipeline():
    t = table(2000)
    scan = MemoryScanExec.from_arrow(t, batch_rows=256)
    plan = ProjectExec(
        FilterExec(scan, [BinaryExpr(">", col(1), lit(50.0))]),
        [col(0), BinaryExpr("*", col(1), lit(2.0))], ["a", "b2"])
    got = plan.execute_collect().to_arrow()
    df = t.to_pandas()
    want = df[df.b > 50.0]
    assert got.num_rows == len(want)
    assert np.allclose(np.sort(got.column(1).to_numpy()),
                       np.sort((want.b * 2).to_numpy()))


def test_limit():
    t = table(500)
    scan = MemoryScanExec.from_arrow(t, batch_rows=64)
    plan = LimitExec(scan, 100)
    assert plan.execute_collect().num_rows == 100
    plan2 = LimitExec(MemoryScanExec.from_arrow(t), 9999)
    assert plan2.execute_collect().num_rows == 500


def test_union_and_rename():
    t1, t2 = table(100, 1), table(150, 2)
    u = UnionExec([MemoryScanExec.from_arrow(t1), MemoryScanExec.from_arrow(t2)])
    assert u.execute_collect().num_rows == 250
    r = RenameColumnsExec(MemoryScanExec.from_arrow(t1), ["x", "y", "z"])
    assert r.schema.names == ["x", "y", "z"]
    assert r.execute_collect().to_arrow().schema.names == ["x", "y", "z"]


def test_expand_grouping_sets():
    t = pa.table({"k": pa.array([1, 2]), "v": pa.array([10, 20])})
    scan = MemoryScanExec.from_arrow(t)
    plan = ExpandExec(scan, [
        [col(0), col(1)],
        [lit(None, S.INT64), col(1)],
    ], ["k", "v"])
    got = plan.execute_collect().to_arrow()
    assert got.num_rows == 4
    ks = sorted(got.column(0).to_pylist(), key=lambda x: (x is None, x))
    assert ks == [1, 2, None, None]


def test_sort_basic_asc_desc_nulls():
    t = pa.table({
        "k": pa.array([3, None, 1, 2, None, 0]),
        "v": pa.array(["c", "x", "a", "b", "y", "z"]),
    })
    scan = MemoryScanExec.from_arrow(t)
    plan = SortExec(scan, [(col(0), False, True)])  # asc nulls first
    got = plan.execute_collect().to_arrow()
    assert got.column(0).to_pylist() == [None, None, 0, 1, 2, 3]
    plan2 = SortExec(MemoryScanExec.from_arrow(t), [(col(0), True, False)])
    got2 = plan2.execute_collect().to_arrow()
    assert got2.column(0).to_pylist() == [3, 2, 1, 0, None, None]


def test_sort_multi_key_with_strings():
    t = pa.table({
        "s": pa.array(["b", "a", "b", "a", None]),
        "x": pa.array([2.0, 1.0, 1.0, 2.0, 0.0]),
    })
    plan = SortExec(MemoryScanExec.from_arrow(t),
                    [(col(0), False, True), (col(1), True, True)])
    got = plan.execute_collect().to_arrow()
    assert got.column(0).to_pylist() == [None, "a", "a", "b", "b"]
    assert got.column(1).to_pylist() == [0.0, 2.0, 1.0, 2.0, 1.0]


def test_sort_fuzz_against_numpy():
    rng = np.random.default_rng(7)
    n = 5000
    t = pa.table({
        "a": pa.array(rng.integers(-100, 100, n)),
        "b": pa.array(np.where(rng.random(n) < 0.1, np.nan, rng.random(n))),
    })
    plan = SortExec(MemoryScanExec.from_arrow(t, batch_rows=512),
                    [(col(0), False, True), (col(1), False, True)])
    got = plan.execute_collect().to_arrow()
    df = t.to_pandas().sort_values(["a", "b"], kind="stable")
    assert got.column(0).to_pylist() == df.a.tolist()
    gb = np.array(got.column(1).to_pylist(), dtype=float)
    wb = df.b.to_numpy()
    assert ((gb == wb) | (np.isnan(gb) & np.isnan(wb))).all()


def test_sort_spill_roundtrip():
    """Force spills with a tiny memory budget; result must be identical."""
    rng = np.random.default_rng(3)
    n = 20000
    t = pa.table({"a": pa.array(rng.integers(0, 10000, n)),
                  "p": pa.array(rng.random(n))})
    MemManager.init(200_000)  # ~200KB: forces multiple spilled runs
    try:
        plan = SortExec(MemoryScanExec.from_arrow(t, batch_rows=2048),
                        [(col(0), False, True)])
        got = plan.execute_collect().to_arrow()
        assert plan.metrics.get("spill_count") >= 1 or True  # metrics on op
        want = np.sort(t.column("a").to_numpy())
        assert np.array_equal(got.column(0).to_numpy(), want)
        assert got.num_rows == n
    finally:
        MemManager.init(default := None or 4 << 30)


def test_sort_fetch_topk():
    t = table(1000)
    plan = SortExec(MemoryScanExec.from_arrow(t),
                    [(col(1), True, False)], fetch=10)
    got = plan.execute_collect().to_arrow()
    assert got.num_rows == 10
    want = np.sort(t.column("b").to_numpy())[::-1][:10]
    assert np.allclose(got.column(1).to_numpy(), want)


def test_parquet_scan_with_pruning(tmp_path):
    t = pa.table({"k": pa.array(range(10000)),
                  "v": pa.array(np.arange(10000) * 0.5)})
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path, row_group_size=1000)
    pred = BinaryExpr(">", col(0, "k"), lit(8500))
    scan = ParquetScanExec(S.Schema.from_arrow(t.schema), [[path]],
                           predicate=pred)
    plan = FilterExec(scan, [pred])
    got = plan.execute_collect().to_arrow()
    assert got.num_rows == 1499
    assert scan.metrics.get("pruned_row_groups") == 8


def test_parquet_scan_projection(tmp_path):
    t = table(100)
    path = str(tmp_path / "p.parquet")
    pq.write_table(t, path)
    scan = ParquetScanExec(S.Schema.from_arrow(t.schema), [[path]],
                           projection=["s", "a"])
    got = scan.execute_collect().to_arrow()
    assert got.schema.names == ["s", "a"]
    assert got.num_rows == 100


def test_orc_scan_roundtrip(tmp_path):
    from pyarrow import orc
    from blaze_tpu.ops.orc import OrcScanExec
    t = table(500)
    path = str(tmp_path / "t.orc")
    orc.write_table(t, path)
    scan = OrcScanExec(S.Schema.from_arrow(t.schema), [[path]],
                       projection=["a", "b"])
    got = scan.execute_collect().to_arrow()
    assert got.num_rows == 500
    assert got.schema.names == ["a", "b"]


def test_fs_provider_local_and_callback():
    import io
    from blaze_tpu.bridge.fs import CallbackFs, fs_provider
    blobs = {"x://data/f1": b"hello"}
    fs_provider.register("x", CallbackFs(lambda p: io.BytesIO(blobs[p])))
    f = fs_provider.provide("x://data/f1").open("x://data/f1")
    assert f.read() == b"hello"
    assert fs_provider.provide("/tmp").__class__.__name__ == "LocalFs"


def test_sort_decimal_order_host_path():
    # decimal ORDER BY must order by value, not the truncated integer part
    # (ADVICE r1 high: _host_order_key decimal truncation)
    import decimal as pydec
    vals = ["0.20", "-0.50", "1.45", "1.23", None, "-0.49"]
    t = pa.table({"d": pa.array(
        [None if v is None else pydec.Decimal(v) for v in vals],
        type=pa.decimal128(12, 2))})
    plan = SortExec(MemoryScanExec.from_arrow(t, batch_rows=4), [(col(0), False, True)])
    out = pa.Table.from_batches(
        [b.to_arrow() for b in plan.execute(0)])
    got = [None if v is None else str(v) for v in out.column(0).to_pylist()]
    assert got == [None, "-0.50", "-0.49", "0.20", "1.23", "1.45"]
    # descending, nulls last
    plan = SortExec(MemoryScanExec.from_arrow(t, batch_rows=4), [(col(0), True, False)])
    out = pa.Table.from_batches([b.to_arrow() for b in plan.execute(0)])
    got = [None if v is None else str(v) for v in out.column(0).to_pylist()]
    assert got == ["1.45", "1.23", "0.20", "-0.49", "-0.50", None]


def test_project_multi_batch_does_not_replay_first_batch():
    """Regression: the projection evaluator cache must reset per batch —
    a stale entry replays batch 1's columns into every later batch."""
    import numpy as np
    import pyarrow as pa
    from blaze_tpu.exprs import col
    from blaze_tpu.ops import MemoryScanExec, ProjectExec
    n = 10_000
    t = pa.table({"a": pa.array(np.arange(n)),
                  "b": pa.array(np.arange(n) * 2.0)})
    scan = MemoryScanExec.from_arrow(t, batch_rows=1024)
    proj = ProjectExec(scan, [col(0), col(1)], ["a", "b"])
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in proj.execute(0)])
    assert out.num_rows == n
    assert np.array_equal(np.asarray(out["a"].combine_chunks()),
                          np.arange(n))


def test_merge_string_keys_with_trailing_nul():
    """Regression: a bytes threshold scalar must not lose trailing NUL
    bytes when compared against object-dtype key arrays (numpy S-dtype
    coercion), or spilled-run merges emit rows out of order."""
    import numpy as np
    import pyarrow as pa
    from blaze_tpu.ops.sort import _count_leq, host_sort_keys

    rb = pa.record_batch([pa.array(["a", "a\x00", "a\x01"])], names=["s"])
    keys = host_sort_keys(rb, [0], [False], [True])
    threshold = tuple(k[1] for k in keys)  # the "a\x00" row
    assert _count_leq(keys, threshold) == 2


def test_sort_multibatch_string_keys_merge(tmp_path):
    """External merge over spilled runs with string keys incl. NULs."""
    import numpy as np
    import pyarrow as pa
    from blaze_tpu import config
    from blaze_tpu.exprs import col
    from blaze_tpu.memory import MemManager
    from blaze_tpu.ops import MemoryScanExec, SortExec

    rng = np.random.default_rng(0)
    vals = [f"k{i % 97}\x00{i % 7}" for i in range(20_000)]
    t = pa.table({"s": pa.array(vals)})
    MemManager.init(128 << 10)  # force spills
    try:
        plan = SortExec(MemoryScanExec.from_arrow(t, batch_rows=2048),
                        [(col(0), False, True)])
        got = pa.Table.from_batches(
            [b.compact().to_arrow() for b in plan.execute(0)])
    finally:
        MemManager.init(4 << 30)
    out = got["s"].to_pylist()
    assert out == sorted(vals, key=lambda s: s.encode())


class TestOrcSchemaEvolution:
    """ORC schema-evolution vectors (ref orc_exec.rs evolution confs:
    `auron.orc.force.positional.evolution` + by-name matching against
    files whose physical schema drifted from the table schema)."""

    def _write(self, tmp_path, name, tbl):
        from pyarrow import orc
        path = str(tmp_path / name)
        orc.write_table(tbl, path)
        return path

    def test_by_name_ignores_column_order(self, tmp_path):
        import pyarrow as pa
        from blaze_tpu.ops.orc import OrcScanExec
        # file columns physically reordered vs the declared schema
        declared = pa.table({"a": pa.array([1, 2, 3]),
                             "b": pa.array([1.5, 2.5, 3.5])})
        drifted = pa.table({"b": pa.array([1.5, 2.5, 3.5]),
                            "a": pa.array([1, 2, 3])})
        path = self._write(tmp_path, "drift.orc", drifted)
        scan = OrcScanExec(S.Schema.from_arrow(declared.schema), [[path]],
                           projection=["a", "b"])
        got = scan.execute_collect().to_arrow()
        assert got.column("a").to_pylist() == [1, 2, 3]
        assert got.column("b").to_pylist() == [1.5, 2.5, 3.5]

    def test_positional_evolution_matches_by_index(self, tmp_path):
        import pyarrow as pa
        from blaze_tpu import config
        from blaze_tpu.ops.orc import OrcScanExec
        # hive-style rename: physical names differ, positions agree
        declared = pa.table({"a": pa.array([7, 8]),
                             "b": pa.array([0.5, 1.5])})
        renamed = pa.table({"_col0": pa.array([7, 8]),
                            "_col1": pa.array([0.5, 1.5])})
        path = self._write(tmp_path, "renamed.orc", renamed)
        config.conf.set(config.ORC_FORCE_POSITIONAL_EVOLUTION.key, True)
        try:
            scan = OrcScanExec(S.Schema.from_arrow(declared.schema),
                               [[path]], projection=["a", "b"])
            got = scan.execute_collect().to_arrow()
        finally:
            config.conf.unset(config.ORC_FORCE_POSITIONAL_EVOLUTION.key)
        assert got.schema.names == ["a", "b"]
        assert got.column("a").to_pylist() == [7, 8]
        assert got.column("b").to_pylist() == [0.5, 1.5]

    def test_positional_evolution_reordered_projection(self, tmp_path):
        import pyarrow as pa
        from blaze_tpu import config
        from blaze_tpu.ops.orc import OrcScanExec
        # projection order differs from file order: pyarrow returns
        # requested columns in FILE order, so naive rename mislabels
        declared = pa.table({"a": pa.array([7, 8]),
                             "b": pa.array([0.5, 1.5])})
        renamed = pa.table({"_col0": pa.array([7, 8]),
                            "_col1": pa.array([0.5, 1.5])})
        path = self._write(tmp_path, "reord.orc", renamed)
        config.conf.set(config.ORC_FORCE_POSITIONAL_EVOLUTION.key, True)
        try:
            scan = OrcScanExec(S.Schema.from_arrow(declared.schema),
                               [[path]], projection=["b", "a"])
            got = scan.execute_collect().to_arrow()
        finally:
            config.conf.unset(config.ORC_FORCE_POSITIONAL_EVOLUTION.key)
        assert got.schema.names == ["b", "a"]
        assert got.column("a").to_pylist() == [7, 8]
        assert got.column("b").to_pylist() == [0.5, 1.5]

    def test_added_column_missing_in_old_file(self, tmp_path):
        import pyarrow as pa
        from blaze_tpu.ops.orc import OrcScanExec
        # table evolved: column c added after the file was written
        old = pa.table({"a": pa.array([1, 2])})
        declared = pa.table({"a": pa.array([1, 2]),
                             "c": pa.array([None, None],
                                           type=pa.int64())})
        path = self._write(tmp_path, "old.orc", old)
        scan = OrcScanExec(S.Schema.from_arrow(declared.schema), [[path]],
                           projection=["a", "c"])
        got = scan.execute_collect().to_arrow()
        assert got.column("a").to_pylist() == [1, 2]
        assert got.column("c").null_count == 2

    def test_widened_int_type(self, tmp_path):
        import pyarrow as pa
        from blaze_tpu.ops.orc import OrcScanExec
        # int32 file column read under an int64 table schema
        old = pa.table({"a": pa.array([5, 6], type=pa.int32())})
        declared = pa.schema([("a", pa.int64())])
        path = self._write(tmp_path, "narrow.orc", old)
        scan = OrcScanExec(S.Schema.from_arrow(declared), [[path]],
                           projection=["a"])
        got = scan.execute_collect().to_arrow()
        assert got.schema.field("a").type == pa.int64()
        assert got.column("a").to_pylist() == [5, 6]

    def test_no_projected_column_in_file_yields_null_rows(self, tmp_path):
        import pyarrow as pa
        from blaze_tpu.ops.orc import OrcScanExec
        old = pa.table({"a": pa.array([1, 2, 3])})
        declared = pa.schema([("a", pa.int64()), ("c", pa.int64())])
        path = self._write(tmp_path, "noproj.orc", old)
        scan = OrcScanExec(S.Schema.from_arrow(declared), [[path]],
                           projection=["c"])
        got = scan.execute_collect().to_arrow()
        assert got.num_rows == 3          # rows survive
        assert got.column("c").null_count == 3


def test_orc_stripe_streaming_and_metrics(tmp_path):
    """Multi-stripe files stream stripe by stripe (bounded memory) and
    count scanned bytes (orc_exec.rs poll-per-batch analog)."""
    from pyarrow import orc
    from blaze_tpu.ops.orc import OrcScanExec
    from blaze_tpu.schema import Schema
    n = 200_000
    t = pa.table({"a": pa.array(range(n)),
                  "b": pa.array([float(i) for i in range(n)])})
    path = str(tmp_path / "big.orc")
    orc.write_table(t, path, stripe_size=64 * 1024)
    assert orc.ORCFile(path).nstripes > 4  # really multi-stripe
    scan = OrcScanExec(Schema.from_arrow(t.schema), [[path]])
    total = 0
    for cb in scan.execute(0):
        total += cb.num_rows
    assert total == n
    assert (scan.collect_metrics().get("io_bytes") or 0) > 0


def test_orc_partition_constants(tmp_path):
    from pyarrow import orc
    from blaze_tpu.ops.orc import OrcScanExec
    from blaze_tpu.schema import INT64, Field, Schema, UTF8
    t = pa.table({"v": pa.array([1, 2, 3])})
    path = str(tmp_path / "p.orc")
    orc.write_table(t, path)
    scan = OrcScanExec(
        Schema.from_arrow(t.schema), [[path]],
        projection=["ds", "v"],
        partition_schema=Schema([Field("ds", UTF8)]),
        partition_values=[[["2024-05-05"]]])
    out = pa.Table.from_batches(
        [b.compact().to_arrow() for b in scan.execute(0)])
    assert out.column_names == ["ds", "v"]
    assert set(out.column("ds").to_pylist()) == {"2024-05-05"}
    assert out.column("v").to_pylist() == [1, 2, 3]


def test_orc_cancellation_between_stripes(tmp_path):
    from pyarrow import orc
    from blaze_tpu.bridge.context import (TaskKilledError, current_task)
    from blaze_tpu.ops.orc import OrcScanExec
    from blaze_tpu.schema import Schema
    n = 200_000
    t = pa.table({"a": pa.array(range(n))})
    path = str(tmp_path / "c.orc")
    orc.write_table(t, path, stripe_size=64 * 1024)
    scan = OrcScanExec(Schema.from_arrow(t.schema), [[path]])
    ctx = current_task()
    old = ctx.is_running
    seen = 0

    def kill_after_first():
        return seen == 0
    ctx.is_running = kill_after_first
    try:
        with pytest.raises(TaskKilledError):
            for cb in scan.execute(0):
                seen += cb.num_rows
        assert 0 < seen < n  # produced some stripes, then stopped
    finally:
        ctx.is_running = old


def test_orc_empty_file_yields_no_rows(tmp_path):
    """Hive/Spark writers routinely emit 0-row ORC files (nstripes==0);
    the stripe loop must emit nothing, not read a nonexistent stripe."""
    from pyarrow import orc
    from blaze_tpu.ops.orc import OrcScanExec
    from blaze_tpu.schema import Schema
    t = pa.table({"a": pa.array([], pa.int64())})
    path = str(tmp_path / "empty.orc")
    orc.write_table(t, path)
    scan = OrcScanExec(Schema.from_arrow(t.schema), [[path]])
    assert list(scan.execute(0)) == []
