"""MXU fused-agg strategy (plan/fused.py _execute_mxu + kernels/mxu_agg):
planning eligibility, result parity with the eager path through the
scatter reference formulation, drain bookkeeping and the fixed-point
verify fallback."""

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import config
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.ops import (AggExec, AggMode, FilterExec, MemoryScanExec,
                           make_agg)
from blaze_tpu.plan.fused import FusedPartialAggExec, fuse_plan


def _table(n=5000, seed=0, nulls=True, dirty_amt=False):
    rng = np.random.default_rng(seed)
    amt = np.round(rng.random(n) * 500 - 100, 2)
    if dirty_amt:
        amt[::97] = 1.234567891  # not 2-decimal fixed point
    cust = rng.integers(1, 200, n)
    cust_arr = pa.array(cust)
    if nulls:
        mask = rng.random(n) < 0.05
        cust_arr = pa.array(np.where(mask, None, cust).tolist(),
                            type=pa.int64())
    amask = rng.random(n) < 0.03
    return pa.table({
        "date": pa.array(rng.integers(100, 200, n)),
        "cust": cust_arr,
        "store": pa.array(rng.integers(1, 13, n)),
        "amt": pa.array(np.where(amask, None, amt).tolist(),
                        type=pa.float64()),
        "qty": pa.array(rng.integers(-50, 1000, n)),
    })


def _plan(t, aggs=None):
    scan = MemoryScanExec.from_arrow(t)
    flt = FilterExec(scan, [BinaryExpr(">", col(0, "date"), lit(150))])
    aggs = aggs or [
        (make_agg("sum", [col(3)]), AggMode.PARTIAL, "amt_sum"),
        (make_agg("sum", [col(4)]), AggMode.PARTIAL, "qty_sum"),
        (make_agg("count", [col(3)]), AggMode.PARTIAL, "cnt"),
        (make_agg("count", []), AggMode.PARTIAL, "cnt_star"),
        (make_agg("min", [col(4)]), AggMode.PARTIAL, "qty_min"),
        (make_agg("max", [col(3)]), AggMode.PARTIAL, "amt_max"),
    ]
    return AggExec(flt,
                   [(col(1, "cust"), "cust"), (col(2, "store"), "store")],
                   aggs)


def _collect(plan):
    out = [b.compact().to_arrow() for b in plan.execute(0)]
    out = [b for b in out if b.num_rows]
    t = pa.Table.from_batches(out, schema=plan.schema.to_arrow())
    return t.to_pandas().sort_values(["cust", "store"]).reset_index(
        drop=True)


@pytest.fixture
def mxu_forced():
    config.conf.set(config.AGG_MXU_FORCE.key, True)
    # keep the host-vectorized path out of the way so the MXU branch runs
    config.conf.set(config.FUSED_HOST_VECTORIZED_ENABLE.key, False)
    try:
        yield
    finally:
        config.conf.unset(config.AGG_MXU_FORCE.key)
        config.conf.unset(config.FUSED_HOST_VECTORIZED_ENABLE.key)


class TestPlanning:
    def test_meta_planned_for_bounded_specs(self):
        fused = fuse_plan(_plan(_table()))
        assert isinstance(fused, FusedPartialAggExec)
        assert fused.fused_mode == "dense"
        assert fused._mxu_meta is not None
        kinds = [s.kind for s in fused._mxu_meta.specs]
        assert kinds == ["sum", "sum", "count", "count_star", "min", "max"]
        # float sum rides the fixed-point tier
        amt = fused._mxu_meta.specs[0]
        assert amt.is_float and amt.scale == 100
        qty = fused._mxu_meta.specs[1]
        assert not qty.is_float and qty.scale == 1 and qty.off == -50

    def test_meta_absent_when_slots_exceed_cap(self):
        config.conf.set(config.AGG_MXU_MAX_SLOTS.key, 64)
        try:
            fused = fuse_plan(_plan(_table()))
            assert fused._mxu_meta is None
        finally:
            config.conf.unset(config.AGG_MXU_MAX_SLOTS.key)

    def test_meta_absent_without_value_stats(self):
        # avg is never fused; a sum over a projected computed column has
        # no source stats -> no meta, scatter path still available
        t = _table()
        scan = MemoryScanExec.from_arrow(t)
        flt = FilterExec(scan, [BinaryExpr(">", col(0, "date"), lit(150))])
        agg = AggExec(flt, [(col(2, "store"), "store")],
                      [(make_agg("sum",
                                 [BinaryExpr("+", col(3), col(3))]),
                        AggMode.PARTIAL, "s")])
        fused = fuse_plan(agg)
        assert isinstance(fused, FusedPartialAggExec)
        assert fused._mxu_meta is None


class TestExecutionParity:
    def test_matches_eager(self, mxu_forced):
        t = _table()
        eager = _plan(t)
        fused = fuse_plan(_plan(t))
        assert fused._mxu_meta is not None
        a, b = _collect(eager), _collect(fused)
        assert int(fused.metrics.get("mxu_rows")) > 0
        assert len(a) == len(b)
        for c in a.columns:
            np.testing.assert_allclose(
                a[c].to_numpy(dtype=float), b[c].to_numpy(dtype=float),
                rtol=1e-12, err_msg=c)

    def test_exact_float_sums(self, mxu_forced):
        # the limb path must reproduce the exact decimal sum, which is
        # within 1e-12 of any f64 accumulation order
        t = _table(n=20000, nulls=False)
        fused = fuse_plan(_plan(t))
        got = _collect(fused)
        df = t.to_pandas()
        df = df[df["date"] > 150]
        want = df.groupby(["cust", "store"])["amt"].sum(min_count=1)
        got_idx = got.set_index(["cust", "store"])["amt_sum.sum"]
        for k, v in want.items():
            if np.isnan(v):
                assert np.isnan(got_idx[k])  # all-null group sums null
            else:
                assert abs(got_idx[k] - v) <= 1e-9 * max(1.0, abs(v))

    def test_drain_boundary(self, mxu_forced, monkeypatch):
        # force a drain every window: multi-window accumulation must add
        # tables, not overwrite them
        from blaze_tpu.kernels import mxu_agg
        monkeypatch.setattr(mxu_agg, "MAX_ROWS_PER_TABLE", 1)
        t = _table(n=4000)
        eager = _plan(t)
        fused = fuse_plan(_plan(t))
        a, b = _collect(eager), _collect(fused)
        for c in a.columns:
            np.testing.assert_allclose(
                a[c].to_numpy(dtype=float), b[c].to_numpy(dtype=float),
                rtol=1e-12, err_msg=c)

    def test_verify_failure_falls_back_to_scatter(self, mxu_forced):
        t = _table(n=3000, dirty_amt=True)
        eager = _plan(t)
        fused = fuse_plan(_plan(t))
        assert fused._mxu_meta is not None
        a, b = _collect(eager), _collect(fused)
        assert int(fused.metrics.get("mxu_verify_fallback")) == 1
        for c in a.columns:
            np.testing.assert_allclose(
                a[c].to_numpy(dtype=float), b[c].to_numpy(dtype=float),
                rtol=1e-9, err_msg=c)

    def test_all_rows_filtered(self, mxu_forced):
        t = _table(n=200)
        scan = MemoryScanExec.from_arrow(t)
        flt = FilterExec(scan, [BinaryExpr(">", col(0, "date"), lit(999))])
        agg = AggExec(flt, [(col(2, "store"), "store")],
                      [(make_agg("sum", [col(3)]), AggMode.PARTIAL, "s")])
        fused = fuse_plan(agg)
        rows = [b for b in fused.execute(0) if b.num_rows]
        assert rows == []
