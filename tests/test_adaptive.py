"""Adaptive query execution (plan/adaptive.py): the three runtime
rewrite rules at the stage boundary, history-seeded planning from
statstore priors, and the contracts every rewrite must keep — derived
fingerprints, bit-identical results vs the static plan, lineage
recovery, cancellation, and a byte-identical disabled path."""

import copy
import os
import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import adaptive, advisor, statstore
from blaze_tpu.plan import fingerprint as fp_mod
from blaze_tpu.plan.stages import DagScheduler


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    adaptive.reset_conf_probe()
    statstore.reset_conf_probe()
    try:
        yield
    finally:
        faults.clear()
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)
        for opt in (config.AQE_ENABLE, config.AQE_BROADCAST_THRESHOLD,
                    config.AQE_COALESCE_TARGET, config.AQE_SKEW_FACTOR,
                    config.AQE_SKEW_MAX_SPLITS, config.AQE_HISTORY_SEED,
                    config.STATS_ENABLE, config.STATS_DIR):
            config.conf.unset(opt.key)
        adaptive.reset_conf_probe()
        statstore.reset_conf_probe()


@pytest.fixture
def fast_retries():
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 1)
    try:
        yield
    finally:
        config.conf.unset(config.TASK_RETRY_BACKOFF_MS.key)


def _aqe_on(**extra):
    config.conf.set(config.AQE_ENABLE.key, True)
    for k, v in extra.items():
        config.conf.set(k, v)
    adaptive.reset_conf_probe()


_SCHEMA = lambda a, b: {"fields": [
    {"name": a, "type": {"id": "int64"}, "nullable": True},
    {"name": b, "type": {"id": "float64"}, "nullable": True}]}


def _write_splits(tmp_path, name, t, nsplit):
    paths = []
    step = -(-t.num_rows // nsplit)
    for i in range(nsplit):
        p = str(tmp_path / f"{name}-{i}.parquet")
        pq.write_table(t.slice(i * step, step), p)
        paths.append([p])
    return paths


def _exchange(inp, nparts):
    return {"kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": nparts},
            "input": inp}


def _scan(schema, groups):
    return {"kind": "parquet_scan", "schema": schema,
            "file_groups": groups}


def _join_plan(tmp_path, nparts=8, skewed=False, seed=3):
    """dim (small, BUILD side, left) shuffle-joined with fact; with
    `skewed`, ~70% of fact rows share one key."""
    rng = np.random.default_rng(seed)
    n = 40_000
    if skewed:
        keys = np.where(rng.random(n) < 0.7, 0,
                        rng.integers(1, 200, n)).astype(np.int64)
    else:
        keys = rng.integers(0, 200, n).astype(np.int64)
    fact = pa.table({"k": pa.array(keys), "v": pa.array(rng.random(n))})
    dim = pa.table({"k": pa.array(np.arange(200, dtype=np.int64)),
                    "w": pa.array(rng.random(200))})
    return {"kind": "hash_join", "join_type": "inner",
            "left": _exchange(_scan(_SCHEMA("k", "w"),
                                    _write_splits(tmp_path, "dim", dim,
                                                  2)), nparts),
            "right": _exchange(_scan(_SCHEMA("k", "v"),
                                     _write_splits(tmp_path, "fact",
                                                   fact, 4)), nparts),
            "left_keys": [{"kind": "column", "index": 0}],
            "right_keys": [{"kind": "column", "index": 0}],
            "build_side": "left"}


def _agg_plan(tmp_path, nparts=16, seed=5):
    rng = np.random.default_rng(seed)
    n = 30_000
    t = pa.table({"k": pa.array(rng.integers(0, 500, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    return {"kind": "hash_agg",
            "groupings": [{"expr": {"kind": "column", "index": 0},
                           "name": "k"}],
            "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                      "args": [{"kind": "column", "index": 1}]}],
            "input": _exchange({
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": _scan(_SCHEMA("k", "v"),
                               _write_splits(tmp_path, "in", t, 2))},
                nparts)}


def _canon(t):
    """Canonical frame: a rewrite may change task count and thus row
    order, so equality is order-insensitive."""
    df = t.to_pandas().set_axis(range(t.num_columns), axis=1)
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _run(plan, tmp_path, tag):
    sched = DagScheduler(work_dir=str(tmp_path / f"dag-{tag}"))
    return sched.run_collect(copy.deepcopy(plan)), sched


def _aqe_delta(fn):
    before = xla_stats.aqe_stats()
    out = fn()
    after = xla_stats.aqe_stats()
    return out, {k: after[k] - before[k]
                 for k in after if after[k] != before[k]}


# -- defaults & disabled path ------------------------------------------------

def test_aqe_knobs_default_off():
    assert config.AQE_ENABLE.get() is False
    assert config.AQE_HISTORY_SEED.get() is False
    assert config.AQE_BROADCAST_THRESHOLD.get() == -1   # inherit advisor
    assert config.AQE_SKEW_FACTOR.get() <= 0            # inherit advisor
    assert not adaptive.enabled()
    assert not adaptive.history_seed_enabled()
    assert adaptive.runtime_for(object()) is None


def test_disabled_path_untouched(tmp_path):
    plan = _join_plan(tmp_path)
    # seed_plan must return the SAME object (not a copy) when off
    assert adaptive.seed_plan(plan) is plan
    (got, sched), delta = _aqe_delta(lambda: _run(plan, tmp_path, "off"))
    assert delta == {}
    assert sched.aqe_events == []
    assert all(st.aqe is None for st in sched.stages)
    assert len(sched.stages) == 3  # static shape: 2 producers + result


def test_aqe_footer_silent_at_zero():
    from blaze_tpu.plan.explain import format_aqe_footer
    assert format_aqe_footer({}) is None
    assert format_aqe_footer({"aqe_rewrites": 0,
                              "aqe_history_seeds": 0}) is None
    line = format_aqe_footer({"aqe_rewrites": 2, "aqe_skew_splits": 1,
                              "aqe_bytes_saved": 2048})
    assert line.startswith("aqe: rewrites=2")
    assert "skew_splits=1" in line and "saved=2.0KiB" in line


# -- the three runtime rules -------------------------------------------------

def test_broadcast_switch_bit_identical(tmp_path):
    plan = _join_plan(tmp_path)
    static, _ = _run(plan, tmp_path, "static")
    _aqe_on()
    (got, sched), delta = _aqe_delta(lambda: _run(plan, tmp_path, "aqe"))
    assert delta.get("aqe_broadcast_switches") == 1
    assert delta.get("aqe_stages_elided") == 1
    assert delta.get("aqe_bytes_saved", 0) > 0
    ev = [e for e in sched.aqe_events if e["rule"] == "broadcast"]
    assert len(ev) == 1
    elided = ev[0]["elided_stage"]
    assert sched.stage_placement[elided] == {"compute": "elided",
                                             "exchange": "elided"}
    assert _canon(got).equals(_canon(static))
    # no scheduler leaks even with the derived registrations
    assert all(not v for v in sched.leak_report().values())


def test_skew_split_bit_identical(tmp_path):
    plan = _join_plan(tmp_path, skewed=True)
    static, s0 = _run(plan, tmp_path, "static")
    _aqe_on(**{config.AQE_BROADCAST_THRESHOLD.key: 0,   # force past rule 1
               config.AQE_SKEW_FACTOR.key: 2.0})
    (got, sched), delta = _aqe_delta(lambda: _run(plan, tmp_path, "aqe"))
    assert delta.get("aqe_skew_splits") == 1
    ev = [e for e in sched.aqe_events if e["rule"] == "skew_split"]
    assert len(ev) == 1 and ev[0]["splits"] >= 2
    # the composed rewrite both splits the hot partition and coalesces
    # the tiny remainder (Spark's OptimizeSkewedJoin + coalesce pair)
    assert delta.get("aqe_partitions_coalesced", 0) > 0
    assert sched.stages[-1].num_tasks != s0.stages[-1].num_tasks
    assert _canon(got).equals(_canon(static))


def test_coalesce_bit_identical(tmp_path):
    plan = _agg_plan(tmp_path, nparts=16)
    static, _ = _run(plan, tmp_path, "static")
    _aqe_on()
    (got, sched), delta = _aqe_delta(lambda: _run(plan, tmp_path, "aqe"))
    assert delta.get("aqe_partitions_coalesced") == 15
    assert sched.stages[-1].num_tasks == 1  # tiny data: one task
    assert sched.stages[-1].aqe["rule"] == "coalesce"
    assert _canon(got).equals(_canon(static))


# -- rewrite contracts -------------------------------------------------------

def test_derived_fingerprints_deterministic_and_distinct():
    base = fp_mod.plan_fingerprint({"kind": "debug"})
    a = fp_mod.derived_fingerprint(base, "coalesce", {"groups": [[0, 1]]})
    b = fp_mod.derived_fingerprint(base, "coalesce", {"groups": [[0, 1]]})
    c = fp_mod.derived_fingerprint(base, "coalesce", {"groups": [[0], [1]]})
    d = fp_mod.derived_fingerprint(base, "skew_split", {"groups": [[0, 1]]})
    assert a == b
    assert len({a, c, d, base}) == 4


def test_rewritten_stage_skips_subplan_cache(tmp_path):
    """A rewritten stage must never publish under the static shape's
    identity — the subplan cache key declines when stage.aqe is set."""
    plan = _agg_plan(tmp_path)
    _aqe_on()
    _, sched = _run(plan, tmp_path, "aqe")
    st = sched.stages[-1]
    assert st.aqe is not None
    assert sched._subplan_cache_key(st) is None


def test_rewrite_survives_lineage_recovery(tmp_path, fast_retries):
    plan = _join_plan(tmp_path, skewed=True)
    static, _ = _run(plan, tmp_path, "static")
    _aqe_on(**{config.AQE_BROADCAST_THRESHOLD.key: 0,
               config.AQE_SKEW_FACTOR.key: 2.0})
    xla_stats.reset()
    # corrupt the first frame flushed (stage 0 / map 0): the rewritten
    # consumer's derived readers must surface it as a FetchFailedError
    # naming the original producer map task, and recovery must re-run
    # exactly that task
    with faults.scoped(("shuffle-write", dict(at=(1,), action="corrupt"))):
        got, sched = _run(plan, tmp_path, "aqe")
    assert any(e["rule"] == "skew_split" for e in sched.aqe_events)
    fs = xla_stats.fault_stats()
    assert fs["stage_recoveries"] >= 1
    assert fs["recovered_map_tasks"] >= 1
    assert _canon(got).equals(_canon(static))
    assert all(not v for v in sched.leak_report().values())


def test_rewrite_cancellation_clean(tmp_path):
    from blaze_tpu.serving import QueryCancelled, QueryContext
    plan = _join_plan(tmp_path, skewed=True)
    _aqe_on(**{config.AQE_BROADCAST_THRESHOLD.key: 0,
               config.AQE_SKEW_FACTOR.key: 2.0})
    ctx = QueryContext("q-aqe-cancel")
    sched = DagScheduler(work_dir=str(tmp_path / "dag"),
                         query_ctx=ctx)

    done = threading.Event()

    def cancel_after_rewrite():
        # fire the cancel as soon as the skew rewrite lands, so the
        # rewritten consumer's tasks are what get cancelled
        while not done.wait(0.001):
            if sched.aqe_events:
                ctx.cancel("test cancel after rewrite")
                return

    t = threading.Thread(target=cancel_after_rewrite, daemon=True)
    t.start()
    try:
        with pytest.raises(QueryCancelled):
            sched.run_collect(copy.deepcopy(plan))
            ctx.check()  # raced past the read path: surface it here
    finally:
        done.set()
        t.join(5)
        sched.cleanup()
    assert all(not v for v in sched.leak_report().values())


# -- history-seeded planning -------------------------------------------------

def _stats_on(tmp_path):
    config.conf.set(config.STATS_ENABLE.key, True)
    config.conf.set(config.STATS_DIR.key, str(tmp_path / "stats"))
    statstore.reset_conf_probe()


def test_history_seed_cold_vs_warm(tmp_path):
    plan = _join_plan(tmp_path)
    static, _ = _run(plan, tmp_path, "static")
    _stats_on(tmp_path)
    _aqe_on(**{config.AQE_HISTORY_SEED.key: True})
    # cold: no prior -> no seeding; the runtime broadcast rule still
    # fires from observed bytes, and the boundary lands in the store
    cold, s1 = _run(plan, tmp_path, "cold")
    assert not any(str(e.get("rule", "")).startswith("seed_")
                   for e in s1.aqe_events)
    assert len(s1.stages) == 3
    # warm: the prior pre-broadcasts the historically-small build at
    # BIND time -> both exchanges spliced out, single-stage plan
    (warm, s2), delta = _aqe_delta(lambda: _run(plan, tmp_path, "warm"))
    seeds = [e for e in s2.aqe_events if e["rule"] == "seed_broadcast"]
    assert len(seeds) == 1 and seeds[0]["stage"] is None
    assert delta.get("aqe_history_seeds") == 1
    assert len(s2.stages) < len(s1.stages)
    assert _canon(warm).equals(_canon(static))
    assert _canon(cold).equals(_canon(static))


def test_empty_and_corrupted_statstore_fall_back(tmp_path):
    plan = _join_plan(tmp_path)
    _stats_on(tmp_path)
    _aqe_on(**{config.AQE_HISTORY_SEED.key: True})
    # empty store: static plan, zero errors
    got1, s1 = _run(plan, tmp_path, "empty")
    assert len(s1.stages) == 3

    # corrupt every store file in place: seeding must silently fall
    # back to the static plan (prior() returns None on corruption)
    sdir = str(tmp_path / "stats")
    assert os.path.isdir(sdir) and os.listdir(sdir)
    for name in os.listdir(sdir):
        with open(os.path.join(sdir, name), "w") as f:
            f.write("{not json")
    got2, s2 = _run(plan, tmp_path, "corrupt")
    assert not any(str(e.get("rule", "")).startswith("seed_")
                   for e in s2.aqe_events)
    assert len(s2.stages) == 3
    assert _canon(got2).equals(_canon(got1))


def test_seed_plan_exception_falls_back(tmp_path, monkeypatch):
    _stats_on(tmp_path)
    _aqe_on(**{config.AQE_HISTORY_SEED.key: True})
    monkeypatch.setattr(statstore, "prior",
                        lambda fp: (_ for _ in ()).throw(RuntimeError()))
    plan = {"kind": "debug"}
    assert adaptive.seed_plan(plan) is plan


def test_seed_partitions_unified_across_join(tmp_path, monkeypatch):
    """History says both join inputs are tiny: the seeded plan shrinks
    BOTH exchanges to one unified count (co-partitioning preserved)."""
    plan = _join_plan(tmp_path, nparts=8)
    _stats_on(tmp_path)
    _aqe_on(**{config.AQE_HISTORY_SEED.key: True,
               config.AQE_BROADCAST_THRESHOLD.key: 0})  # no broadcast seed
    sfps = [adaptive._exchange_sfp(plan[s]) for s in ("left", "right")]
    assert all(sfps)
    sk = statstore.sketch_add(statstore.sketch_new(), [1 << 20])  # 1MiB p50
    prior = {"stages": {sfp: {"sid": i, "partitions": 8,
                              "total_bytes": copy.deepcopy(sk)}
                        for i, sfp in enumerate(sfps)}}
    monkeypatch.setattr(statstore, "prior", lambda fp: prior)
    seeded = adaptive.seed_plan(copy.deepcopy(plan))
    ln = seeded["left"]["partitioning"]["num_partitions"]
    rn = seeded["right"]["partitioning"]["num_partitions"]
    assert ln == rn == 1  # 1MiB / 16MiB target -> 1 partition, unified


def test_seed_agg_skip_threads_hint_to_exec(tmp_path, monkeypatch):
    """A high historical probe ratio seeds supports_partial_skipping on
    the partial hash_agg, and the planner threads it to AggExec."""
    plan = _agg_plan(tmp_path)
    _stats_on(tmp_path)
    _aqe_on(**{config.AQE_HISTORY_SEED.key: True})
    monkeypatch.setattr(statstore, "prior",
                        lambda fp: {"derived": {"agg_probe_ratio": 0.97}})
    seeded = adaptive.seed_plan(copy.deepcopy(plan))
    partial = seeded["input"]["input"]
    assert partial["kind"] == "hash_agg"
    assert partial["supports_partial_skipping"] is True
    # the final (top) agg must NOT carry the hint: modes are not partial
    assert not plan["input"]["input"].get("supports_partial_skipping")
    assert not seeded.get("supports_partial_skipping")
    from blaze_tpu.plan import create_plan
    ex = create_plan(partial)
    assert ex.skip_partial_hint is True
    assert create_plan(plan["input"]["input"]).skip_partial_hint is False


# -- advisor & progress integration ------------------------------------------

def test_advisor_recommendations_match_findings():
    record = {"stages": {
        "fp-small": {"sid": 1, "partitions": 8,
                     "total_bytes": statstore.sketch_add(
                         statstore.sketch_new(), [1024.0]),
                     "last_partition_bytes": [10, 10, 10, 10]},
        "fp-skew": {"sid": 2, "partitions": 4,
                    "total_bytes": statstore.sketch_add(
                        statstore.sketch_new(), [1 << 30]),
                    "last_partition_bytes": [100, 100, 10_000, 100]},
    }}
    recs = advisor.recommendations(record)
    assert [(r["rule"], r["stage"]) for r in recs] == \
        [("broadcast", 1), ("skew_split", 2)]
    for r in recs:
        assert set(r) == {"rule", "stage", "fingerprint", "threshold",
                          "evidence"}
        assert r["evidence"]["fingerprint"] == r["fingerprint"]
    # findings are rendered FROM the same records: same stages flagged
    kinds = [(f["kind"], f["stage"]) for f in advisor.findings(record)]
    assert ("broadcast_candidate", 1) in kinds
    assert ("skew_partition", 2) in kinds
    assert recs[0]["threshold"] == advisor.broadcast_threshold()
    assert recs[1]["threshold"] == advisor.skew_factor()


def test_progress_eta_reestimates_after_replan():
    from blaze_tpu.serving import progress
    progress.reset()
    try:
        progress.note_query_start("q-replan", fingerprint="fp",
                                  prior_wall_s=100.0)
        progress.note_stage_start("q-replan", 0, 8)
        for _ in range(4):
            progress.note_task_done("q-replan", 0)
        snap = progress.progress("q-replan")
        assert snap["eta_source"] == "prior"       # trusts history...
        assert snap["replans"] == 0
        progress.note_stage_replan("q-replan", 0, 2)
        snap = progress.progress("q-replan")
        # ...until a rewrite invalidates the static-plan prior
        assert snap["replans"] == 1
        assert snap["eta_source"] == "fraction-replanned"
        assert snap["stages"]["0"]["tasks_total"] == 6  # 4 done + 2 new
        assert snap["eta_s"] is not None
    finally:
        progress.reset()


def test_aqe_counters_in_families_and_snapshot():
    fams = xla_stats.counter_families()
    assert "aqe" in fams
    assert set(fams["aqe"]) == {
        "aqe_rewrites", "aqe_broadcast_switches",
        "aqe_partitions_coalesced", "aqe_skew_splits",
        "aqe_history_seeds", "aqe_bytes_saved", "aqe_stages_elided"}
    xla_stats.note_aqe(rewrites=2, bytes_saved=10)
    try:
        snap = xla_stats.snapshot()
        assert snap["aqe_rewrites"] >= 2
        assert snap["aqe_bytes_saved"] >= 10
    finally:
        xla_stats.reset()


def test_aqe_spans_emitted_when_tracing_enabled(tmp_path):
    """A rewrite emits an `aqe_rewrite` instant and a seeded bind an
    `aqe_history_seed` instant (registered names; conformance-checked
    by tests/test_span_names.py)."""
    from blaze_tpu.bridge import tracing

    def drain():
        tracing.stop_tracing()
        with tracing._lock:
            tracing._spans.clear()

    config.conf.set(config.TRACE_ENABLE.key, "on")
    tracing.reset_conf_probe()
    drain()
    try:
        _aqe_on()
        _run(_join_plan(tmp_path), tmp_path, "span-bc")
        names = [s["name"] for s in tracing.spans()]
        rewrites = [s for s in tracing.spans()
                    if s["name"] == "aqe_rewrite"]
        assert rewrites and rewrites[0]["attrs"]["rule"] == "broadcast"

        # warm a statstore prior, then a seeded bind
        config.conf.set(config.STATS_ENABLE.key, True)
        config.conf.set(config.STATS_DIR.key, str(tmp_path / "stats"))
        config.conf.set(config.AQE_HISTORY_SEED.key, True)
        statstore.reset_conf_probe()
        adaptive.reset_conf_probe()
        _run(_join_plan(tmp_path), tmp_path, "span-cold")
        drain()
        tracing.reset_conf_probe()
        _run(_join_plan(tmp_path), tmp_path, "span-warm")
        seeds = [s for s in tracing.spans()
                 if s["name"] == "aqe_history_seed"]
        assert seeds and seeds[0]["attrs"]["seeds"] >= 1
    finally:
        config.conf.unset(config.TRACE_ENABLE.key)
        tracing.reset_conf_probe()
        drain()
