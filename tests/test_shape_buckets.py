"""Shape bucketing: the capacity ladder bounds the static-shape universe
jit kernels see, so a ragged multi-batch pipeline compiles each kernel at
most once per bucket and not at all once warm (batch.bucket_capacity +
plan/fused.py _pad_lane; verified through meter_jit counters)."""

import numpy as np
import pyarrow as pa

from blaze_tpu import config
from blaze_tpu.batch import (LANE, ColumnBatch, bucket_capacity,
                             bucket_ladder, round_capacity)
from blaze_tpu.bridge import xla_stats
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.ops import (AggExec, AggMode, FilterExec, MemoryScanExec,
                           ProjectExec, make_agg)
from blaze_tpu.plan.fused import FusedPartialAggExec, fuse_plan
from blaze_tpu.schema import Schema

# ragged tail sizes spanning four default-ladder rungs:
# {128, 256, 512, 1024}
RAGGED = [100, 200, 450, 700, 512, 333, 64, 1000]


# -- the ladder itself (tier-1 regression for the default config) -----------

def test_default_bucket_ladder_monotone_and_lane_aligned():
    ladder = bucket_ladder(1 << 22)
    assert ladder == sorted(set(ladder)), "ladder must be strictly monotone"
    assert all(c % LANE == 0 for c in ladder), "rungs must be lane-aligned"
    # geometric: ~log2(4M/128) rungs, not one per size
    assert len(ladder) <= 20
    assert ladder[0] == LANE and ladder[-1] >= (1 << 22)


def test_bucket_capacity_on_ladder_and_covers_request():
    ladder = set(bucket_ladder(1 << 22))
    for n in range(0, 70000, 777):
        cap = bucket_capacity(n)
        assert cap >= max(n, LANE)
        assert cap in ladder
        assert cap % LANE == 0


def test_bucket_capacity_disabled_degrades_to_lane_rounding():
    with config.scoped(**{"auron.tpu.batch.bucketing": False}):
        for n in (0, 1, 100, 300, 5000, 70001):
            assert bucket_capacity(n) == round_capacity(n)


def test_bucket_capacity_custom_ladder():
    with config.scoped(**{"auron.tpu.batch.bucket.min": 1000,
                          "auron.tpu.batch.bucket.growth": 4.0}):
        base = round_capacity(1000)
        assert bucket_capacity(10) == base
        assert bucket_capacity(base + 1) == round_capacity(base * 4)


def test_bucket_stats_reach_profiler_snapshot():
    cap_small, cap_big = bucket_capacity(100), bucket_capacity(5000)
    before = xla_stats.snapshot()
    bucket_capacity(100)
    bucket_capacity(5000)
    d = xla_stats.delta(before)
    assert d["bucket_batches"] == 2
    assert d["bucket_pad_rows"] == (cap_small - 100) + (cap_big - 5000)
    caps = xla_stats.pipeline_stats()["bucket_capacities"]
    assert cap_small in caps and cap_big in caps


# -- ragged pipelines compile once per (kernel, bucket) ----------------------

def _table(n):
    rng = np.random.default_rng(7)
    return pa.table({
        "date": pa.array(rng.integers(100, 200, n)),
        "cust": pa.array(rng.integers(1, 50, n).astype(np.int64)),
        "amt": pa.array(np.round(rng.random(n) * 100, 2)),
    })


def _ragged_scan(t):
    """MemoryScanExec yielding one batch per RAGGED size (each batch keeps
    its own ragged length, like parquet row-group tails)."""
    batches, off = [], 0
    for n in RAGGED:
        batches.append(ColumnBatch.from_arrow(
            pa.Table.from_batches(t.slice(off, n).to_batches())))
        off += n
    return MemoryScanExec(Schema.from_arrow(t.schema), [batches])


def _pipeline(t, fused):
    scan = _ragged_scan(t)
    flt = FilterExec(scan, [BinaryExpr(">", col(0, "date"), lit(120))])
    proj = ProjectExec(flt, [col(1, "cust"), col(2, "amt")],
                       ["cust", "amt"])
    agg = AggExec(proj, [(col(0, "cust"), "cust")],
                  [(make_agg("sum", [col(1)]), AggMode.PARTIAL, "amt_sum"),
                   (make_agg("count", [col(1)]), AggMode.PARTIAL, "cnt")])
    return fuse_plan(agg) if fused else agg


def _run(plan):
    total = 0
    for b in plan.execute(0):
        total += b.selected_count()
    return total


def _compiles_by_kernel():
    return {k: v["compiles"]
            for k, v in xla_stats.compile_report()["kernels"].items()}


def _kernel_delta(before, after):
    return {k: after[k] - before.get(k, 0) for k in after
            if after[k] - before.get(k, 0)}


def _assert_bounded_compiles(fused):
    t = _table(sum(RAGGED))
    n_buckets = len({bucket_capacity(n) for n in RAGGED})
    assert n_buckets == 4  # the scenario spans several rungs

    before = _compiles_by_kernel()
    rows1 = _run(_pipeline(t, fused))
    warm = _compiles_by_kernel()
    first = _kernel_delta(before, warm)
    for kernel, compiles in first.items():
        assert compiles <= n_buckets, \
            f"{kernel}: {compiles} compiles > {n_buckets} buckets"

    # steady state: a second (fresh) plan over the same data recompiles
    # NOTHING — every shape is already a known bucket
    rows2 = _run(_pipeline(t, fused))
    second = _kernel_delta(warm, _compiles_by_kernel())
    assert second == {}, f"steady-state recompiles: {second}"
    assert rows1 == rows2


def test_eager_pipeline_compiles_bounded_by_buckets():
    with config.scoped(**{"auron.tpu.fused.stage.enable": False}):
        _assert_bounded_compiles(fused=False)


def test_fused_pipeline_compiles_bounded_by_buckets():
    # force the jit stage kernels (the host-vectorized Arrow path would
    # bypass XLA entirely under host placement)
    with config.scoped(**{"auron.tpu.fused.hostVectorized": False}):
        plan = _pipeline(_table(sum(RAGGED)), fused=True)
        assert isinstance(plan, FusedPartialAggExec)
        _assert_bounded_compiles(fused=True)


def test_explain_analyze_surfaces_bucket_stats():
    from blaze_tpu.plan import explain_analyze
    with config.scoped(**{"auron.tpu.fused.hostVectorized": False}):
        prof = explain_analyze(_pipeline(_table(sum(RAGGED)), fused=True),
                               record=False)
    assert prof.xla.get("bucket_batches", 0) > 0
    assert "batch shaping:" in prof.render_text()


def test_fused_pipeline_jit_kernels_actually_run():
    """Guard against the bounded-compiles assertions passing vacuously:
    the dense fused path must dispatch metered kernels."""
    with config.scoped(**{"auron.tpu.fused.hostVectorized": False}):
        t = _table(sum(RAGGED))
        before = xla_stats.snapshot()
        _run(_pipeline(t, fused=True))
        d = xla_stats.delta(before)
        assert d["total_calls"] > 0
