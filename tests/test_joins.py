"""Join tests over the join-type matrix, modeled on the reference's
joins/test.rs (1,249 LoC SMJ/BHJ/SHJ x join-type matrix, SURVEY.md §4)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from blaze_tpu import schema as S
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import MemoryScanExec
from blaze_tpu.ops.joins import (BroadcastJoinExec, JoinType,
                                 ShuffledHashJoinExec, SortMergeJoinExec)


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


LEFT = pa.table({
    "lk": pa.array([1, 2, 3, 4, None, 2], type=pa.int64()),
    "lv": pa.array(["a", "b", "c", "d", "e", "f"]),
})
RIGHT = pa.table({
    "rk": pa.array([2, 2, 3, 5, None], type=pa.int64()),
    "rv": pa.array([20, 21, 30, 50, 99], type=pa.int64()),
})


def join(how, left=LEFT, right=RIGHT, cls=ShuffledHashJoinExec,
         build_side="right", flt=None):
    plan = cls(MemoryScanExec.from_arrow(left),
               MemoryScanExec.from_arrow(right),
               [col(0, "lk")], [col(0, "rk")], how,
               build_side=build_side, join_filter=flt)
    got = plan.execute_collect().to_arrow()
    return got


def rows(tbl, cols=None):
    names = cols or tbl.schema.names
    data = [tbl.column(n).to_pylist() for n in names]
    return sorted(zip(*data), key=lambda r: tuple((x is None, x) for x in r))


def test_inner_join():
    got = join(JoinType.INNER)
    assert rows(got, ["lk", "lv", "rv"]) == sorted([
        (2, "b", 20), (2, "b", 21), (2, "f", 20), (2, "f", 21), (3, "c", 30)])


def test_left_outer():
    got = join(JoinType.LEFT)
    r = rows(got, ["lk", "lv", "rv"])
    want = sorted([(2, "b", 20), (2, "b", 21), (2, "f", 20), (2, "f", 21),
                   (3, "c", 30), (1, "a", None), (4, "d", None),
                   (None, "e", None)],
                  key=lambda t: tuple((x is None, x) for x in t))
    assert r == want


def test_right_outer():
    got = join(JoinType.RIGHT)
    r = rows(got, ["lk", "rk", "rv"])
    want = sorted([(2, 2, 20), (2, 2, 21), (2, 2, 20), (2, 2, 21),
                   (3, 3, 30), (None, 5, 50), (None, None, 99)],
                  key=lambda t: tuple((x is None, x) for x in t))
    assert r == want


def test_full_outer():
    got = join(JoinType.FULL)
    assert got.num_rows == 5 + 3 + 2  # matches + unmatched left + unmatched right


def test_left_semi_and_anti():
    semi = join(JoinType.LEFT_SEMI)
    assert sorted(semi.column("lv").to_pylist()) == ["b", "c", "f"]
    anti = join(JoinType.LEFT_ANTI)
    assert sorted(anti.column("lv").to_pylist()) == ["a", "d", "e"]


def test_right_semi_and_anti():
    semi = join(JoinType.RIGHT_SEMI)
    assert sorted(semi.column("rv").to_pylist()) == [20, 21, 30]
    anti = join(JoinType.RIGHT_ANTI)
    assert sorted(anti.column("rv").to_pylist()) == [50, 99]


def test_existence_join():
    got = join(JoinType.EXISTENCE)
    d = dict(zip(got.column("lv").to_pylist(),
                 got.column("exists").to_pylist()))
    assert d == {"a": False, "b": True, "c": True, "d": False, "e": False,
                 "f": True}


def test_join_filter():
    # inner join with residual filter rv > 20
    flt = BinaryExpr(">", col(3, "rv"), lit(20))
    got = join(JoinType.INNER, flt=flt)
    assert rows(got, ["lk", "lv", "rv"]) == sorted([
        (2, "b", 21), (2, "f", 21), (3, "c", 30)])


def test_broadcast_join_build_left():
    got = join(JoinType.INNER, cls=BroadcastJoinExec, build_side="left")
    assert got.num_rows == 5


def test_string_keys_join():
    l = pa.table({"k": pa.array(["x", "y", None, "z"]),
                  "v": pa.array([1, 2, 3, 4])})
    r = pa.table({"k": pa.array(["y", "z", "z", None]),
                  "w": pa.array([20, 30, 31, 40])})
    plan = ShuffledHashJoinExec(
        MemoryScanExec.from_arrow(l), MemoryScanExec.from_arrow(r),
        [col(0, "k")], [col(0, "k")], JoinType.INNER)
    got = plan.execute_collect().to_arrow()
    assert sorted(zip(got.column(1).to_pylist(),
                      got.column(3).to_pylist())) == \
        [(2, 20), (4, 30), (4, 31)]


def test_join_fuzz_vs_pandas():
    rng = np.random.default_rng(5)
    n, m = 3000, 2000
    l = pa.table({"k": pa.array(rng.integers(0, 500, n)),
                  "a": pa.array(rng.random(n))})
    r = pa.table({"k": pa.array(rng.integers(0, 500, m)),
                  "b": pa.array(rng.random(m))})
    for how, pd_how in [(JoinType.INNER, "inner"), (JoinType.LEFT, "left"),
                        (JoinType.FULL, "outer")]:
        plan = SortMergeJoinExec(
            MemoryScanExec.from_arrow(l, batch_rows=512),
            MemoryScanExec.from_arrow(r, batch_rows=512),
            [col(0)], [col(0)], how)
        got = plan.execute_collect().to_arrow()
        want = l.to_pandas().merge(r.to_pandas(), on="k", how=pd_how)
        assert got.num_rows == len(want), how
        assert got.column("a").null_count == want.a.isna().sum()


def test_empty_sides():
    empty_r = RIGHT.slice(0, 0)
    got = join(JoinType.INNER, right=empty_r)
    assert got.num_rows == 0
    got2 = join(JoinType.LEFT, right=empty_r)
    assert got2.num_rows == 6
    assert got2.column("rv").null_count == 6
    empty_l = LEFT.slice(0, 0)
    got3 = join(JoinType.FULL, left=empty_l)
    assert got3.num_rows == 5


def test_direct_address_join_vs_pandas():
    """The single-int-key direct-address fast path (_direct_join_once:
    unique dense build keys -> slot-array lookup instead of Acero).
    Dense unique build keys with probe nulls + out-of-range keys, all
    probe-driven join types, against a pandas oracle."""
    rng = np.random.default_rng(11)
    n = 5000
    build_keys = np.arange(100, 400)  # dense, unique: direct-eligible
    rng.shuffle(build_keys)
    build = pa.table({"bk": pa.array(build_keys, type=pa.int64()),
                      "bv": pa.array(rng.random(len(build_keys)))})
    pk = rng.integers(0, 500, n)  # ~60% in range
    probe = pa.table({
        "pk": pa.array([None if i % 37 == 0 else int(pk[i])
                        for i in range(n)], type=pa.int64()),
        "pv": pa.array(rng.random(n))})
    pdp, pdb = probe.to_pandas(), build.to_pandas()

    def mk(how, build_side):
        left, right = (probe, build) if build_side == "right" \
            else (build, probe)
        lk, rk = (("pk", "bk") if build_side == "right" else ("bk", "pk"))
        plan = BroadcastJoinExec(
            MemoryScanExec.from_arrow(left),
            MemoryScanExec.from_arrow(right),
            [col(0, lk)], [col(0, rk)], how, build_side=build_side)
        got = plan.execute_collect().to_arrow()
        assert plan.metrics.get("direct_join_rows") > 0 or \
            got.num_rows == 0, "direct path must engage"
        return got

    got = mk(JoinType.INNER, "right")
    want = pdp.merge(pdb, left_on="pk", right_on="bk", how="inner")
    assert got.num_rows == len(want)
    assert abs(sum(x or 0 for x in got.column("bv").to_pylist())
               - want.bv.sum()) < 1e-6

    got = mk(JoinType.LEFT, "right")
    want = pdp.merge(pdb, left_on="pk", right_on="bk", how="left")
    assert got.num_rows == len(want)
    assert got.column("bv").null_count == int(want.bv.isna().sum())

    got = mk(JoinType.LEFT_SEMI, "right")
    matched = pdp[pdp.pk.isin(pdb.bk)]
    assert got.num_rows == len(matched)

    got = mk(JoinType.LEFT_ANTI, "right")
    assert got.num_rows == n - len(matched)  # nulls kept by anti

    # probe on the right (build_side=left): RIGHT outer + semi/anti
    got = mk(JoinType.RIGHT, "left")
    want = pdb.merge(pdp, left_on="bk", right_on="pk", how="right")
    assert got.num_rows == len(want)
    got = mk(JoinType.RIGHT_SEMI, "left")
    assert got.num_rows == len(matched)
    got = mk(JoinType.RIGHT_ANTI, "left")
    assert got.num_rows == n - len(matched)


def test_direct_join_falls_back_on_duplicates():
    """Duplicate build keys require pair expansion -> Acero fallback;
    results must stay identical to the oracle."""
    build = pa.table({"bk": pa.array([1, 2, 2, 3], type=pa.int64()),
                      "bv": pa.array([10, 20, 21, 30], type=pa.int64())})
    probe = pa.table({"pk": pa.array([2, 3, 4], type=pa.int64()),
                      "pv": pa.array(["x", "y", "z"])})
    plan = BroadcastJoinExec(
        MemoryScanExec.from_arrow(probe),
        MemoryScanExec.from_arrow(build),
        [col(0, "pk")], [col(0, "bk")], JoinType.INNER,
        build_side="right")
    got = plan.execute_collect().to_arrow()
    assert plan.metrics.get("direct_join_rows") == 0
    assert got.num_rows == 3  # (2,20) (2,21) (3,30)
