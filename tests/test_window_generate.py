"""Window + Generate operator tests (ref window_exec.rs / generate_exec.rs
unit tests, SURVEY.md §4 tier 1)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from blaze_tpu import schema as S
from blaze_tpu.exprs import col, lit
from blaze_tpu.memory import MemManager
from blaze_tpu.ops import MemoryScanExec, SortExec, make_agg
from blaze_tpu.ops.generate import (ExplodeGenerator, GenerateExec,
                                    JsonTupleGenerator, UDTFGenerator)
from blaze_tpu.ops.window import (LeadLagFunc, NthValueFunc, RankFunc,
                                  WindowAggFunc, WindowExec, WindowRankType)


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def sorted_scan(t, part_col, order_col):
    scan = MemoryScanExec.from_arrow(t, batch_rows=64)
    return SortExec(scan, [(col(part_col), False, True),
                           (col(order_col), False, True)])


T = pa.table({
    "g": pa.array([1, 1, 1, 2, 2, 2, 2]),
    "v": pa.array([10, 20, 20, 5, 6, 7, 7]),
})


def test_rank_family():
    plan = WindowExec(
        sorted_scan(T, 0, 1),
        [RankFunc("rn", WindowRankType.ROW_NUMBER),
         RankFunc("rk", WindowRankType.RANK),
         RankFunc("dr", WindowRankType.DENSE_RANK),
         RankFunc("pr", WindowRankType.PERCENT_RANK),
         RankFunc("cd", WindowRankType.CUME_DIST)],
        [col(0)], [(col(1), False, True)])
    got = plan.execute_collect().to_arrow()
    assert got.column("rn").to_pylist() == [1, 2, 3, 1, 2, 3, 4]
    assert got.column("rk").to_pylist() == [1, 2, 2, 1, 2, 3, 3]
    assert got.column("dr").to_pylist() == [1, 2, 2, 1, 2, 3, 3]
    assert got.column("pr").to_pylist() == pytest.approx(
        [0.0, 0.5, 0.5, 0.0, 1 / 3, 2 / 3, 2 / 3])
    assert got.column("cd").to_pylist() == pytest.approx(
        [1 / 3, 1.0, 1.0, 0.25, 0.5, 1.0, 1.0])


def test_window_group_limit():
    plan = WindowExec(sorted_scan(T, 0, 1),
                      [RankFunc("rk", WindowRankType.RANK)],
                      [col(0)], [(col(1), False, True)], group_limit=2)
    got = plan.execute_collect().to_arrow()
    assert got.column("rk").to_pylist() == [1, 2, 2, 1, 2]


def test_lead_lag_nth():
    plan = WindowExec(
        sorted_scan(T, 0, 1),
        [LeadLagFunc("ld", col(1), 1), LeadLagFunc("lg", col(1), -1, -99),
         NthValueFunc("n2", col(1), 2)],
        [col(0)], [(col(1), False, True)])
    got = plan.execute_collect().to_arrow()
    assert got.column("ld").to_pylist() == [20, 20, None, 6, 7, 7, None]
    assert got.column("lg").to_pylist() == [-99, 10, 20, -99, 5, 6, 7]
    assert got.column("n2").to_pylist() == [20, 20, 20, 6, 6, 6, 6]


def test_running_and_whole_partition_agg():
    plan = WindowExec(
        sorted_scan(T, 0, 1),
        [WindowAggFunc("rs", make_agg("sum", [col(1)]), running=True),
         WindowAggFunc("ts", make_agg("sum", [col(1)]), running=False),
         WindowAggFunc("rc", make_agg("count", [col(1)]), running=True)],
        [col(0)], [(col(1), False, True)])
    got = plan.execute_collect().to_arrow()
    # RANGE frame: tied order values share the frame end (Spark default)
    assert got.column("rs").to_pylist() == [10, 50, 50, 5, 11, 25, 25]
    assert got.column("ts").to_pylist() == [50, 50, 50, 25, 25, 25, 25]
    assert got.column("rc").to_pylist() == [1, 3, 3, 1, 2, 4, 4]


def test_window_no_partition():
    plan = WindowExec(
        SortExec(MemoryScanExec.from_arrow(T), [(col(1), False, True)]),
        [RankFunc("rn", WindowRankType.ROW_NUMBER)],
        [], [(col(1), False, True)])
    got = plan.execute_collect().to_arrow()
    assert got.column("rn").to_pylist() == list(range(1, 8))


def test_explode_list():
    t = pa.table({
        "id": pa.array([1, 2, 3, 4]),
        "xs": pa.array([[1, 2], [], None, [5]], type=pa.list_(pa.int64())),
    })
    plan = GenerateExec(MemoryScanExec.from_arrow(t),
                        ExplodeGenerator(col(1)), required_cols=[0])
    got = plan.execute_collect().to_arrow()
    assert got.column("id").to_pylist() == [1, 1, 4]
    assert got.column("col").to_pylist() == [1, 2, 5]


def test_explode_outer_and_pos():
    t = pa.table({
        "id": pa.array([1, 2]),
        "xs": pa.array([[7, 8], None], type=pa.list_(pa.int64())),
    })
    plan = GenerateExec(MemoryScanExec.from_arrow(t),
                        ExplodeGenerator(col(1), position=True, outer=True),
                        required_cols=[0])
    got = plan.execute_collect().to_arrow()
    assert got.column("id").to_pylist() == [1, 1, 2]
    assert got.column("pos").to_pylist() == [0, 1, None]
    assert got.column("col").to_pylist() == [7, 8, None]


def test_explode_map():
    t = pa.table({
        "id": pa.array([1, 2]),
        "m": pa.array([[("a", 1), ("b", 2)], [("c", 3)]],
                      type=pa.map_(pa.utf8(), pa.int64())),
    })
    plan = GenerateExec(MemoryScanExec.from_arrow(t),
                        ExplodeGenerator(col(1)), required_cols=[0])
    got = plan.execute_collect().to_arrow()
    assert got.column("key").to_pylist() == ["a", "b", "c"]
    assert got.column("value").to_pylist() == [1, 2, 3]


def test_json_tuple():
    t = pa.table({"j": pa.array(['{"a": 1, "b": "x"}', 'bad json', None,
                                 '{"a": null, "c": [1,2]}'])})
    plan = GenerateExec(MemoryScanExec.from_arrow(t),
                        JsonTupleGenerator(col(0), ["a", "b", "c"]),
                        required_cols=[])
    got = plan.execute_collect().to_arrow()
    assert got.column("c0").to_pylist() == ["1", None, None, None]
    assert got.column("c1").to_pylist() == ["x", None, None, None]
    assert got.column("c2").to_pylist() == [None, None, None, "[1, 2]"]


def test_udtf():
    t = pa.table({"n": pa.array([2, 0, 3])})
    gen = UDTFGenerator(
        args=[col(0)],
        fn=lambda n: [(i,) for i in range(n)],
        fields=[S.Field("i", S.INT64)])
    plan = GenerateExec(MemoryScanExec.from_arrow(t), gen, required_cols=[0])
    got = plan.execute_collect().to_arrow()
    assert got.column("n").to_pylist() == [2, 2, 3, 3, 3]
    assert got.column("i").to_pylist() == [0, 1, 0, 1, 2]


def test_window_streaming_matches_oneshot():
    # many partitions + small batches: exercises the partition-boundary
    # flush path; result must equal pandas' whole-input computation
    from blaze_tpu import config
    rng = np.random.default_rng(3)
    n = 4000
    t = pa.table({
        "g": pa.array(np.sort(rng.integers(0, 200, n))),
        "v": pa.array(rng.integers(0, 100, n)),
    })
    scan = sorted_scan(t, 0, 1)
    w = WindowExec(scan, [RankFunc("rn", WindowRankType.ROW_NUMBER),
                          WindowAggFunc("s", make_agg("sum", [col(1)]),
                                        running=True)],
                   [col(0)], [(col(1), False, True)])
    with config.scoped(**{config.BATCH_SIZE.key: 256}):
        out = pa.Table.from_batches([b.to_arrow() for b in w.execute(0)])
    # one-shot: same operator over the whole input in a single huge batch
    w2 = WindowExec(sorted_scan(t, 0, 1),
                    [RankFunc("rn", WindowRankType.ROW_NUMBER),
                     WindowAggFunc("s", make_agg("sum", [col(1)]),
                                   running=True)],
                    [col(0)], [(col(1), False, True)])
    with config.scoped(**{config.BATCH_SIZE.key: 1 << 20}):
        out2 = pa.Table.from_batches([b.to_arrow() for b in w2.execute(0)])
    df = out.to_pandas().sort_values(["g", "v", "rn"]).reset_index(drop=True)
    df2 = out2.to_pandas().sort_values(["g", "v", "rn"]).reset_index(drop=True)
    assert len(df) == len(df2) == 4000
    assert (df["s"].values == df2["s"].values).all()
    assert (df["rn"].values == df2["rn"].values).all()


def test_window_buffer_spills_under_pressure():
    from blaze_tpu import config
    rng = np.random.default_rng(5)
    n = 3000
    t = pa.table({
        "g": pa.array(np.sort(rng.integers(0, 50, n))),
        "v": pa.array(np.arange(n)),
    })
    w = WindowExec(sorted_scan(t, 0, 1),
                   [WindowAggFunc("s", make_agg("sum", [col(1)]),
                                  running=True)],
                   [col(0)], [(col(1), False, True)])
    mgr = MemManager.init(64 << 10)  # 64 KiB: forces the buffer to spill
    spills_before = mgr.total_spill_count
    try:
        with config.scoped(**{config.BATCH_SIZE.key: 128}):
            out = pa.Table.from_batches([b.to_arrow() for b in w.execute(0)])
        assert mgr.total_spill_count > spills_before, \
            "expected the window buffer (or its upstream sort) to spill"
    finally:
        MemManager.init(4 << 30)
    df = out.to_pandas().sort_values(["g", "v"]).reset_index(drop=True)
    pdf = t.to_pandas().sort_values(["g", "v"]).reset_index(drop=True)
    pdf["s"] = pdf.groupby("g")["v"].cumsum()
    assert len(df) == n
    assert (df["s"].values == pdf["s"].values).all()
