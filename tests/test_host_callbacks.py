"""End-to-end C-ABI host-callback tests: a simulated host engine registers
conf/FS/spill/task-probe/UDF callbacks through the real shared library
(blaze_register_callbacks), and a plan is driven whose conf, input file,
and UDF all come from the host side (ref JniBridge.java:57+ statics)."""

import ctypes
import io
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu.bridge import host_callbacks
from blaze_tpu.bridge.native import get_host_bridge
from blaze_tpu.memory import MemManager
from blaze_tpu.plan.proto_serde import task_definition_to_bytes


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


class FakeHost:
    """The JVM-side stand-in: conf map, file store, spill store, UDFs."""

    def __init__(self):
        self.conf = {"auron.batch.size": "777"}
        self.files = {}          # path -> bytes
        self.fds = {}            # fd -> (bytes, ...)
        self.next_fd = 1
        self.spills = {}         # id -> bytearray
        self.next_spill = 1
        self.task_running = True
        self.udf_buffers = {}    # addr -> buffer keepalive
        self.calls = []
        self._keepalive = []

    # -- callback bodies ---------------------------------------------------
    def conf_get(self, key, buf, cap):
        self.calls.append(("conf", key.decode()))
        v = self.conf.get(key.decode())
        if v is None:
            return 0
        raw = v.encode("utf-8")[:cap - 1] + b"\x00"
        ctypes.memmove(buf, raw, len(raw))
        return 1

    def fs_open(self, path):
        p = path.decode()
        self.calls.append(("fs_open", p))
        if p not in self.files:
            return -1
        fd = self.next_fd
        self.next_fd += 1
        self.fds[fd] = self.files[p]
        return fd

    def fs_size(self, fd):
        return len(self.fds.get(fd, b""))

    def fs_read(self, fd, offset, buf, length):
        data = self.fds.get(fd)
        if data is None:
            return -1
        chunk = data[offset:offset + length]
        ctypes.memmove(buf, chunk, len(chunk))
        return len(chunk)

    def fs_close(self, fd):
        self.fds.pop(fd, None)

    def spill_create(self):
        sid = self.next_spill
        self.next_spill += 1
        self.spills[sid] = bytearray()
        self.calls.append(("spill_create", sid))
        return sid

    def spill_write(self, sid, buf, length):
        if sid not in self.spills:
            return -1
        self.spills[sid] += ctypes.string_at(buf, length)
        return length

    def spill_read(self, sid, offset, buf, length):
        data = self.spills.get(sid)
        if data is None:
            return -1
        chunk = bytes(data[offset:offset + length])
        ctypes.memmove(buf, chunk, len(chunk))
        return len(chunk)

    def spill_release(self, sid):
        self.spills.pop(sid, None)

    def is_task_running(self, stage, partition):
        return 1 if self.task_running else 0

    def udf_eval(self, name, args, length, out_p, out_len):
        self.calls.append(("udf", name.decode()))
        payload = ctypes.string_at(args, length)
        with pa.ipc.open_stream(io.BytesIO(payload)) as r:
            rb = next(iter(r))
        col0 = rb.column(0)
        result = pa.compute.multiply(col0, 2)
        out_rb = pa.record_batch([result], names=["r"])
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, out_rb.schema) as w:
            w.write_batch(out_rb)
        blob = sink.getvalue()
        buf = ctypes.create_string_buffer(blob, len(blob))
        self.udf_buffers[ctypes.addressof(buf)] = buf
        out_p[0] = ctypes.cast(buf, ctypes.c_void_p).value
        out_len[0] = len(blob)
        return 0

    def free_buffer(self, p):
        self.udf_buffers.pop(p, None)

    # -- struct construction ----------------------------------------------
    # host-side prototypes use writable pointers where the engine writes
    PROTOS = {
        "conf_get": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_char),
                                     ctypes.c_int64),
        "fs_open": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_char_p),
        "fs_size": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64),
        "fs_read": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                                    ctypes.c_int64,
                                    ctypes.POINTER(ctypes.c_uint8),
                                    ctypes.c_int64),
        "fs_close": ctypes.CFUNCTYPE(None, ctypes.c_int64),
        "spill_create": ctypes.CFUNCTYPE(ctypes.c_int64),
        "spill_write": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_uint8),
                                        ctypes.c_int64),
        "spill_read": ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                                       ctypes.c_int64,
                                       ctypes.POINTER(ctypes.c_uint8),
                                       ctypes.c_int64),
        "spill_release": ctypes.CFUNCTYPE(None, ctypes.c_int64),
        "is_task_running": ctypes.CFUNCTYPE(ctypes.c_int32,
                                            ctypes.c_int64,
                                            ctypes.c_int64),
        "udf_eval": ctypes.CFUNCTYPE(
            ctypes.c_int64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64)),
        "free_buffer": ctypes.CFUNCTYPE(None, ctypes.c_void_p),
    }

    def build_struct(self):
        fields = [("version", ctypes.c_int64)] + \
            [(n, ctypes.c_void_p) for n in self.PROTOS]

        class Cbs(ctypes.Structure):
            _fields_ = fields

        cbs = Cbs()
        cbs.version = 1
        for n, proto in self.PROTOS.items():
            fn = proto(getattr(self, n))
            self._keepalive.append(fn)
            setattr(cbs, n, ctypes.cast(fn, ctypes.c_void_p))
        return cbs


@pytest.fixture
def host():
    lib = get_host_bridge()
    if lib is None:
        pytest.skip("host bridge library not built")
    h = FakeHost()
    cbs = h.build_struct()
    lib.blaze_register_callbacks.restype = ctypes.c_int64
    err = ctypes.c_char_p()
    rc = lib.blaze_register_callbacks(ctypes.byref(cbs), ctypes.byref(err))
    assert rc == 0, err.value
    yield h, lib
    host_callbacks.uninstall()


def test_conf_comes_from_host(host):
    h, _lib = host
    assert config.BATCH_SIZE.get() == 777
    assert ("conf", "auron.batch.size") in h.calls
    # engine-side overrides still win over the host layer
    config.conf.set(config.BATCH_SIZE.key, 123)
    try:
        assert config.BATCH_SIZE.get() == 123
    finally:
        config.conf.unset(config.BATCH_SIZE.key)


def test_full_plan_with_host_fs_and_udf(host, tmp_path):
    h, lib = host
    # the input parquet lives only in the HOST's file store
    t = pa.table({"k": pa.array([1, 2, 3, 4], type=pa.int64()),
                  "v": pa.array([10.0, 20.0, 30.0, 40.0])})
    sink = io.BytesIO()
    pq.write_table(t, sink)
    h.files["hostfs://warehouse/t.parquet"] = sink.getvalue()

    plan = {"kind": "project",
            "exprs": [{"kind": "column", "name": "k"},
                      {"kind": "udf", "name": "host_double",
                       "args": [{"kind": "column", "name": "k"}],
                       "type": {"id": "int64"}}],
            "names": ["k", "k2"],
            "input": {"kind": "parquet_scan",
                      "schema": {"fields": [
                          {"name": "k", "type": {"id": "int64"},
                           "nullable": True},
                          {"name": "v", "type": {"id": "float64"},
                           "nullable": True}]},
                      "file_groups": [["hostfs://warehouse/t.parquet"]]}}
    td = task_definition_to_bytes({"stage_id": 0, "partition_id": 0,
                                   "plan": plan})

    lib.blaze_call_native_proto.restype = ctypes.c_int64
    err = ctypes.c_char_p()
    handle = lib.blaze_call_native_proto(td, len(td), ctypes.byref(err))
    assert handle > 0, err.value

    rows = []
    while True:
        data = ctypes.POINTER(ctypes.c_uint8)()
        n = lib.blaze_next_batch(handle, ctypes.byref(data),
                                 ctypes.byref(err))
        assert n >= 0, err.value
        if n == 0:
            break
        blob = ctypes.string_at(data, n)
        lib.blaze_free_buffer(data)
        with pa.ipc.open_stream(io.BytesIO(blob)) as r:
            for rb in r:
                rows.extend(zip(rb.column(0).to_pylist(),
                                rb.column(1).to_pylist()))
    metrics = ctypes.c_char_p()
    assert lib.blaze_finalize_native(handle, ctypes.byref(metrics),
                                    ctypes.byref(err)) == 0
    assert sorted(rows) == [(1, 2), (2, 4), (3, 6), (4, 8)]
    assert ("fs_open", "hostfs://warehouse/t.parquet") in h.calls
    assert any(c == ("udf", "host_double") for c in h.calls)


def test_spill_goes_to_host_engine(host):
    h, _lib = host
    from blaze_tpu.memory.spill import try_new_spill
    s = try_new_spill()
    rb = pa.record_batch([pa.array([1, 2, 3], type=pa.int64())],
                         names=["x"])
    s.write_batches(iter([rb]))
    assert any(c[0] == "spill_create" for c in h.calls)
    assert len(h.spills) == 1
    back = list(s.read_batches())
    assert back[0].column(0).to_pylist() == [1, 2, 3]
    s.release()
    assert len(h.spills) == 0


def test_host_task_probe_kills_running_task(host):
    h, _lib = host
    from blaze_tpu.bridge.context import TaskContext, TaskKilledError
    ctx = TaskContext(stage_id=5, partition_id=2)
    ctx.check_running()  # alive
    h.task_running = False
    with pytest.raises(TaskKilledError):
        ctx.check_running()
