"""Process-isolated worker runtime (ISSUE 11): crash fault domains with
supervised restart, heartbeats, liveness detection, blacklisting, and
lineage-recovery integration.  Every test leaves
`auron.tpu.workers.enable` OFF so the thread path stays the tier-1
seed-verified baseline."""

import io
import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.bridge.tasks import run_tasks
from blaze_tpu.faults import (FetchFailedError, WorkerCrashed,
                              classify_exception, parse_rules)
from blaze_tpu.memory import MemManager
from blaze_tpu.parallel import workers
from blaze_tpu.parallel.workers import (RemoteTaskError, WorkerPool,
                                        WorkerPoolUnavailable, _recv_msg,
                                        _send_msg)
from blaze_tpu.plan.stages import DagScheduler, Stage

ECHO = "blaze_tpu.parallel.workers:_task_echo"
SLEEP = "blaze_tpu.parallel.workers:_task_sleep"
RAISE = "blaze_tpu.parallel.workers:_task_raise"


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    try:
        yield
    finally:
        faults.clear()
        workers.shutdown_pool(wait=False)
        for key in ("auron.tpu.workers.enable", "auron.tpu.workers.count",
                    "auron.tpu.workers.heartbeatMs",
                    "auron.tpu.workers.livenessMs",
                    "auron.tpu.workers.crashBudget",
                    "auron.tpu.workers.restartBackoffMs",
                    "auron.tpu.dag.singleTaskBytes",
                    "auron.tpu.task.retryBackoffMs",
                    "auron.tpu.task.maxAttempts"):
            config.conf.unset(key)


def _pool(count=2, **kw) -> WorkerPool:
    kw.setdefault("heartbeat_ms", 50)
    kw.setdefault("liveness_ms", 2000)
    kw.setdefault("restart_backoff_ms", 10)
    return WorkerPool(count=count, **kw).start()


# -- satellite: parse_rules site validation ---------------------------------

def test_parse_rules_rejects_unknown_site():
    with pytest.raises(ValueError, match="unknown fault site"):
        parse_rules("shufle-write=0.5")  # typo'd site fails LOUDLY
    with pytest.raises(ValueError, match="unknown fault site"):
        parse_rules("task-start=0.5,wroker-crash@1")


def test_parse_rules_accepts_worker_sites_and_registered():
    sites = [s for s, _ in parse_rules(
        "worker-crash=0.25,worker-hang@2,worker-slow=0.1*3")]
    assert sites == ["worker-crash", "worker-hang", "worker-slow"]
    with pytest.raises(ValueError):
        parse_rules("my-plugin-site@1")
    faults.register_site("my-plugin-site")  # escape hatch
    try:
        assert parse_rules("my-plugin-site@1")[0][0] == "my-plugin-site"
    finally:
        faults._extra_sites.discard("my-plugin-site")


# -- pipe framing -----------------------------------------------------------

def test_frame_roundtrip_and_truncation():
    buf = io.BytesIO()
    msgs = [{"kind": "task", "args": (1, "x", [2.5])},
            {"kind": "heartbeat"}]
    for m in msgs:
        _send_msg(buf, m)
    buf.seek(0)
    assert _recv_msg(buf) == msgs[0]
    assert _recv_msg(buf) == msgs[1]
    assert _recv_msg(buf) is None  # clean EOF
    # a torn frame (process killed mid-write) is EOFError — never a
    # partial unpickle
    whole = io.BytesIO()
    _send_msg(whole, msgs[0])
    for cut in (3, 7, len(whole.getvalue()) - 3):
        with pytest.raises(EOFError):
            _recv_msg(io.BytesIO(whole.getvalue()[:cut]))


def test_frame_crc_detects_corruption():
    from blaze_tpu.faults import ShuffleChecksumError
    buf = io.BytesIO()
    _send_msg(buf, {"k": "v"})
    raw = bytearray(buf.getvalue())
    raw[-1] ^= 0xFF  # flip a payload bit
    with pytest.raises(ShuffleChecksumError):
        _recv_msg(io.BytesIO(bytes(raw)))


# -- pool basics ------------------------------------------------------------

def test_pool_echo_and_health():
    pool = _pool(count=2)
    try:
        r = pool.run({"fn": ECHO, "args": (7, "ok")})
        assert r["echo"] == [7, "ok"]
        assert r["pid"] != os.getpid()  # really another process
        assert r["_worker_id"] in (0, 1)
        h = pool.health()
        assert len(h) == 2
        assert all(s["state"] in ("idle", "starting") for s in h)
        assert sum(s["tasks_done"] for s in h) == 1
    finally:
        pool.shutdown()


def test_remote_error_classification_crosses_boundary():
    pool = _pool(count=1)
    try:
        with pytest.raises(FetchFailedError) as ei:
            pool.run({"fn": RAISE, "args": ("fetch",)})
        assert (ei.value.stage_id, ei.value.map_id) == (7, 3)
        with pytest.raises(RemoteTaskError) as ei:
            pool.run({"fn": RAISE, "args": ("retryable",)})
        assert classify_exception(ei.value) == "retryable"
        with pytest.raises(RemoteTaskError) as ei:
            pool.run({"fn": RAISE, "args": ("fatal",)})
        assert classify_exception(ei.value) == "fatal"
        # the worker survived all three failures: errors are not crashes
        assert pool.health()[0]["crashes"] == 0
    finally:
        pool.shutdown()


def test_crash_classified_restarted_and_retry_lands_elsewhere():
    xla_stats.reset()
    pool = _pool(count=2)
    try:
        with faults.scoped(("worker-crash", dict(at=(1,)))):
            with pytest.raises(WorkerCrashed) as ei:
                pool.run({"fn": SLEEP, "args": (0.5, "v")})
        crashed = ei.value.worker_id
        assert crashed is not None
        assert ei.value.exit_code == -9  # really SIGKILLed
        # the retry contract: exclude the crashed worker, land elsewhere
        r = pool.run({"fn": ECHO, "args": ("after",)}, exclude={crashed})
        assert r["_worker_id"] != crashed
        # supervision respawns the crashed slot
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            st = pool.health()[crashed]
            if st["state"] in ("idle", "starting") \
                    and st["incarnation"] == 2:
                break
            time.sleep(0.05)
        assert pool.health()[crashed]["incarnation"] == 2
        ws = xla_stats.worker_stats()
        assert ws["worker_crashes"] == 1
        assert ws["worker_restarts"] >= 1
    finally:
        pool.shutdown()


def test_hang_detected_within_liveness_deadline():
    xla_stats.reset()
    pool = _pool(count=1, heartbeat_ms=25, liveness_ms=400)
    try:
        with faults.scoped(("worker-hang", dict(at=(1,)))):
            t0 = time.monotonic()
            with pytest.raises(WorkerCrashed, match="heartbeat miss"):
                pool.run({"fn": ECHO, "args": (1,)})
            elapsed = time.monotonic() - t0
        # detected by the liveness deadline, not the 10x-liveness wedge
        # sleep expiring (0.4s deadline + supervision slack)
        assert elapsed < 3.0
        assert xla_stats.worker_stats()["worker_hangs"] == 1
    finally:
        pool.shutdown()


def test_slow_worker_not_mistaken_for_dead():
    pool = _pool(count=1, heartbeat_ms=25, liveness_ms=300)
    try:
        # worker-slow stalls the task well past the liveness deadline
        # but KEEPS heartbeating: the pool must wait, not kill
        with faults.scoped(("worker-slow", dict(at=(1,)))):
            r = pool.run({"fn": SLEEP, "args": (0.5, "done")})
        assert r["value"] == "done"
        assert pool.health()[0]["crashes"] == 0
    finally:
        pool.shutdown()


def test_blacklisted_worker_never_receives_tasks():
    xla_stats.reset()
    pool = _pool(count=2, crash_budget=1)
    try:
        victim = None
        with faults.scoped(("worker-crash", dict(at=(1, 2)))):
            for _ in range(2):
                with pytest.raises(WorkerCrashed) as ei:
                    # exclude the healthy worker so BOTH crashes hit the
                    # same slot and exhaust its budget of 1
                    pool.run({"fn": SLEEP, "args": (0.5,)},
                             exclude=set() if victim is None
                             else {1 - victim})
                victim = ei.value.worker_id if victim is None else victim
        assert pool.health()[victim]["state"] == "blacklisted"
        assert xla_stats.worker_stats()["worker_blacklisted"] == 1
        # a blacklisted slot never comes back or takes work
        for _ in range(6):
            r = pool.run({"fn": ECHO, "args": ("x",)})
            assert r["_worker_id"] != victim
        assert pool.health()[victim]["state"] == "blacklisted"
    finally:
        pool.shutdown()


def test_fully_blacklisted_pool_signals_unavailable():
    pool = _pool(count=1, crash_budget=0)
    try:
        with faults.scoped(("worker-crash", dict(at=(1,)))):
            with pytest.raises(WorkerCrashed):
                pool.run({"fn": SLEEP, "args": (0.5,)})
        with pytest.raises(WorkerPoolUnavailable):
            pool.run({"fn": ECHO, "args": (1,)})
    finally:
        pool.shutdown()


# -- satellite: run_tasks timeout regression --------------------------------

def test_run_tasks_timeout_nonblocking_thread_path():
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 1)
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="still"):
        run_tasks(lambda i: time.sleep(8.0), 2, 0.5, "wedge-test",
                  max_workers=2)
    # the wave raises promptly and does NOT join the wedged threads
    assert time.monotonic() - t0 < 5.0


def test_run_tasks_timeout_under_worker_pool_kills_and_recovers():
    config.conf.set(config.WORKERS_ENABLE.key, "true")
    config.conf.set(config.WORKERS_COUNT.key, 1)
    config.conf.set(config.WORKERS_RESTART_BACKOFF_MS.key, 10)
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 1)
    config.conf.set(config.TASK_MAX_ATTEMPTS.key, 1)
    pool = workers.get_pool()
    assert pool is not None
    pool.run({"fn": ECHO, "args": ("warm",)}, timeout_s=60.0)
    xla_stats.reset()
    remote = lambda i: {"fn": SLEEP, "args": (30.0, i)}  # noqa: E731
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        run_tasks(lambda i: None, 1, 1.0, "pool-wedge", remote=remote)
    assert time.monotonic() - t0 < 10.0
    # the deadline escalates INTO the child (cancel -> SIGTERM ->
    # SIGKILL) from the task thread, which may land a poll tick after
    # the wave-level TimeoutError surfaced: no worker slot may be left
    # wedged busy
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline \
            and xla_stats.worker_stats()["worker_cancels"] < 1:
        time.sleep(0.05)
    assert xla_stats.worker_stats()["worker_cancels"] >= 1
    r = pool.run({"fn": ECHO, "args": ("alive",)}, timeout_s=60.0)
    assert r["echo"] == ["alive"]


# -- scheduler integration --------------------------------------------------

def _two_stage_plan(tmp_path, n=20_000, n_reduce=3):
    rng = np.random.default_rng(7)
    t = pa.table({"k": pa.array(rng.integers(0, 200, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"in-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}


def _sorted_df(tbl):
    return tbl.to_pandas().sort_values("k").reset_index(drop=True)


def _enable_workers(count=2):
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 5)
    config.conf.set(config.WORKERS_ENABLE.key, "true")
    config.conf.set(config.WORKERS_COUNT.key, count)
    config.conf.set(config.WORKERS_RESTART_BACKOFF_MS.key, 10)


def test_staged_query_through_pool_bit_identical(tmp_path):
    plan = _two_stage_plan(tmp_path)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag0")).run_collect(plan))
    _enable_workers()
    xla_stats.reset()
    sched = DagScheduler(work_dir=str(tmp_path / "dag1"))
    got = _sorted_df(sched.run_collect(plan))
    assert got.equals(clean)
    ws = xla_stats.worker_stats()
    assert ws["worker_tasks"] == 2  # both map tasks process-isolated
    # per-task metric trees rode the result frames home
    assert sched.stage_metrics[0].to_dict()
    assert all(v == [] for v in sched.leak_report().values())


def test_sigkill_mid_map_task_recovers_via_retry(tmp_path):
    """SIGKILL mid-shuffle-write: tmp+os.replace commit means NO
    committed partial output exists, the retry (on another worker)
    produces the whole output, and the query is bit-identical."""
    plan = _two_stage_plan(tmp_path)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag0")).run_collect(plan))
    _enable_workers()
    xla_stats.reset()
    with faults.scoped(("worker-crash", dict(at=(1,)))):
        sched = DagScheduler(work_dir=str(tmp_path / "dag1"))
        got = _sorted_df(sched.run_collect(plan))
    assert got.equals(clean)
    ws = xla_stats.worker_stats()
    assert ws["worker_crashes"] == 1
    assert ws["worker_tasks"] == 3  # 2 map tasks + 1 crash retry
    # leak_report clean after a crash-recovered query
    assert all(v == [] for v in sched.leak_report().values())
    # the wave retried in place (different worker) — no lineage round
    # was needed because nothing poisoned was ever committed
    assert xla_stats.fault_stats()["task_retries"] >= 1


def test_invalidate_worker_outputs_marks_torn_entries(tmp_path):
    """A crash wedged between the .data and .index commits leaves a
    torn pair: the crash listener re-validates the dead worker's
    entries and poisons exactly the torn one in the map-output table."""
    sched = DagScheduler(work_dir=str(tmp_path / "dag"))
    part = {"kind": "hash", "exprs": [], "num_partitions": 2}
    stage = Stage(sid=0, plan={}, partitioning=part, resource_id="r0",
                  num_tasks=2)
    sched.stages = [stage]
    # map 0: valid committed pair; map 1: .data without .index (torn)
    import struct
    good = sched._map_data_path(0, 0)
    with open(good, "wb") as f:
        f.write(b"\0" * 10)
    with open(good[:-5] + ".index", "wb") as f:
        f.write(struct.pack("<3q", 0, 4, 10))
    torn = sched._map_data_path(0, 1)
    with open(torn, "wb") as f:
        f.write(b"\0" * 10)
    sched._stage_outputs[0] = {0: (good, [0, 4, 10]),
                               1: (torn, [0, 5, 10])}
    sched._map_worker = {(0, 0): 3, (0, 1): 3}
    sched.invalidate_worker_outputs(3)
    assert sched._stage_outputs[0][0] is not None  # survived validation
    assert sched._stage_outputs[0][1] is None      # poisoned
    sched.invalidate_worker_outputs(None)  # no-op, never raises
    sched.cleanup()


def test_pool_disabled_is_default_and_thread_path_untouched(tmp_path):
    assert config.WORKERS_ENABLE.get() is False
    plan = _two_stage_plan(tmp_path, n=4_000)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    xla_stats.reset()
    DagScheduler(work_dir=str(tmp_path / "dag")).run_collect(plan)
    assert xla_stats.worker_stats()["worker_tasks"] == 0
    assert workers.active_pool() is None


# -- satellite: bounded crash soak (runs in tier-1) -------------------------

@pytest.mark.soak
def test_worker_crash_soak_bounded(tmp_path):
    """Seeded worker-crash/worker-hang chaos over repeated staged runs:
    every query bit-identical, no leaks, bounded wall time (<60s)."""
    plan = _two_stage_plan(tmp_path, n=8_000)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag0")).run_collect(plan))
    _enable_workers()
    config.conf.set(config.WORKERS_LIVENESS_MS.key, 500)
    config.conf.set(config.WORKERS_HEARTBEAT_MS.key, 50)
    xla_stats.reset()
    t0 = time.monotonic()
    faults.configure("worker-crash=0.3*2,worker-hang@5", seed=1234)
    try:
        for i in range(4):
            sched = DagScheduler(work_dir=str(tmp_path / f"dag{i + 1}"))
            got = _sorted_df(sched.run_collect(plan))
            assert got.equals(clean), f"divergence in soak round {i}"
            assert all(v == [] for v in sched.leak_report().values())
    finally:
        faults.clear()
    ws = xla_stats.worker_stats()
    assert ws["worker_crashes"] >= 1  # the chaos actually bit
    assert time.monotonic() - t0 < 60.0
