"""Device-resident stage loop (ISSUE 8): the scheduler's loop path is
bit-identical to the staged per-batch executor, records its placement,
falls back WHOLESALE on injected faults and degraded queries (never a
divergent result, never a burned retry), and tears down within one
chunk of a cancellation with a clean leak report."""

import threading

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.bridge.context import TaskContext, task_scope
from blaze_tpu.memory import MemManager
from blaze_tpu.plan.stages import DagScheduler
from blaze_tpu.serving import QueryCancelled, QueryContext


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    try:
        yield
    finally:
        faults.clear()


@pytest.fixture
def loop_on():
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")
    try:
        yield
    finally:
        config.conf.unset(config.STAGE_DEVICE_LOOP_ENABLE.key)


@pytest.fixture
def staged_path():
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


def _two_stage_plan(tmp_path, n=8000, n_reduce=3, tag=""):
    """partial sum -> hash exchange -> final sum.  WIDE int64 keys: the
    compact 0..199 range would take the dense lane, which the stage
    compiler rejects — the loop is the hash lane's fold."""
    rng = np.random.default_rng(7)
    k = rng.integers(0, 200, n) * 1000003 + 17
    t = pa.table({"k": pa.array(k, type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"in{tag}-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}


def _sorted_df(tbl):
    return tbl.to_pandas().sort_values("k").reset_index(drop=True)


def _fused_partial(tmp_path, n=4000, tag="fp"):
    """A standalone fused partial agg (the loop-eligible stage root)."""
    from blaze_tpu.plan.column_pruning import prune_columns
    from blaze_tpu.plan.fused import fuse_plan
    from blaze_tpu.plan.planner import collapse_filter_project, create_plan
    rng = np.random.default_rng(3)
    k = rng.integers(0, 200, n) * 1000003 + 17
    t = pa.table({"k": pa.array(k, type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    p = str(tmp_path / f"{tag}.parquet")
    pq.write_table(t, p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    plan = {"kind": "hash_agg",
            "groupings": [{"expr": {"kind": "column", "index": 0},
                           "name": "k"}],
            "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                      "args": [{"kind": "column", "index": 1}]}],
            "input": {"kind": "parquet_scan", "schema": schema,
                      "file_groups": [[p]]}}
    return fuse_plan(prune_columns(collapse_filter_project(
        create_plan(plan))))


# -- bit-identity + placement -----------------------------------------------

def test_scheduler_loop_bit_identical_and_placed(tmp_path, staged_path,
                                                 loop_on):
    plan = _two_stage_plan(tmp_path)
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "off")
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-off")).run_collect(plan))
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")

    before = xla_stats.snapshot()
    sched = DagScheduler(work_dir=str(tmp_path / "dag-on"))
    got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)  # bit-identical, not approximately equal
    d = xla_stats.delta(before)
    assert d["stage_loop_tasks"] >= 2  # both map tasks took the loop
    assert d["stage_loop_fallbacks"] == 0
    assert d["stage_loop_staged_dispatches_avoided"] >= 0
    comp = {p["compute"] for p in sched.stage_placement.values()}
    assert "device-loop" in comp, sched.stage_placement


def test_fused_execute_loop_vs_staged_identical(tmp_path, loop_on):
    before = xla_stats.snapshot()
    t_on = _fused_partial(tmp_path).execute_collect()
    d = xla_stats.delta(before)
    assert d["stage_loop_tasks"] >= 1  # the loop branch actually ran
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "off")
    t_off = _fused_partial(tmp_path).execute_collect()
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")

    def rows(cb):
        df = pa.Table.from_batches([cb.to_arrow()]).to_pandas()
        return sorted(map(tuple, df.itertuples(index=False)))

    assert rows(t_on) == rows(t_off)


# -- wholesale fallback -----------------------------------------------------

def test_injected_fault_falls_back_wholesale(tmp_path, staged_path,
                                             loop_on):
    plan = _two_stage_plan(tmp_path, tag="flt")
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "off")
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-clean")).run_collect(plan))
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")

    before = xla_stats.snapshot()
    with faults.scoped(("device-loop", dict(p=1.0))):
        sched = DagScheduler(work_dir=str(tmp_path / "dag-chaos"))
        got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)
    d = xla_stats.delta(before)
    assert d["stage_loop_fallbacks"] >= 1
    assert d["stage_loop_tasks"] == 0  # no loop task reached the drain
    # a fallback is an in-attempt re-run, NOT a task retry
    assert d["task_retries"] == 0
    comp = {p["compute"] for p in sched.stage_placement.values()}
    assert "device-loop" not in comp, sched.stage_placement


def test_degraded_query_declines_loop(tmp_path, staged_path, loop_on):
    plan = _two_stage_plan(tmp_path, tag="deg")
    # baseline: the same degraded query with the loop OFF — rung 1 turns
    # the partial agg into a pass-through in BOTH paths, so the declined
    # loop must land on exactly the staged degraded bit pattern
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "off")
    q0 = QueryContext("q-deg-off")
    q0.degrade()
    clean = _sorted_df(DagScheduler(
        work_dir=str(tmp_path / "dag-deg-off"),
        query_ctx=q0).run_collect(plan))
    config.conf.set(config.STAGE_DEVICE_LOOP_ENABLE.key, "on")

    ctx = QueryContext("q-deg-on")
    assert ctx.degrade() == "agg-passthrough"  # rung 1 declines the loop
    before = xla_stats.snapshot()
    sched = DagScheduler(work_dir=str(tmp_path / "dag-deg-on"),
                         query_ctx=ctx)
    got = _sorted_df(sched.run_collect(plan))

    assert got.equals(clean)
    d = xla_stats.delta(before)
    assert d["stage_loop_fallbacks"] >= 1
    assert d["stage_loop_tasks"] == 0


# -- cancellation -----------------------------------------------------------

def test_cancel_noticed_at_chunk_boundary(tmp_path, loop_on):
    """Deterministic mid-loop cancel: the source stream fires the token
    after the first chunk's batches are pulled, so the loop must stop at
    the NEXT chunk boundary — teardown bounded by one chunk."""
    from blaze_tpu.plan import stage_compiler
    from blaze_tpu.runtime import loop as device_loop
    config.conf.set(config.STAGE_DEVICE_LOOP_CHUNK.key, 2)
    config.conf.set(config.BATCH_SIZE.key, 512)
    try:
        fp = _fused_partial(tmp_path, n=6000, tag="cancel")  # ~12 batches
        prog = stage_compiler.compile_task_plan(fp)
        assert prog is not None
        ctx = QueryContext("q-mid-cancel")

        def stream():
            for i, b in enumerate(prog.source.execute(0)):
                if i == 2:  # one full chunk delivered; cancel before next
                    ctx.cancel("mid-loop teardown")
                yield b

        task = TaskContext(query=ctx)
        with task_scope(task):
            with pytest.raises(QueryCancelled):
                device_loop.run_partition(prog, 0, ctx="t",
                                          source_stream=stream())
        # exactly one chunk folded before the boundary check fired
        assert task.loop_chunks == 1, task.loop_chunks
    finally:
        config.conf.unset(config.STAGE_DEVICE_LOOP_CHUNK.key)
        config.conf.unset(config.BATCH_SIZE.key)


def test_cancelled_query_leaves_no_leaks(tmp_path, staged_path, loop_on):
    plan = _two_stage_plan(tmp_path, n=100_000, tag="leak")
    ctx = QueryContext("q-leak")
    timer = threading.Timer(0.05, ctx.cancel, args=("bored",))
    sched = DagScheduler(work_dir=str(tmp_path / "dag-leak"),
                         query_ctx=ctx)
    timer.start()
    try:
        with pytest.raises(QueryCancelled):
            sched.run_collect(plan)
    finally:
        timer.cancel()
    report = sched.leak_report()
    assert all(v == [] for v in report.values()), report
