"""Concurrency chaos soak: the query service under concurrent load with
seeded faults, explicit cancels, and tight deadlines.  Asserts the two
robustness invariants end-to-end: zero divergent SURVIVING queries
(everything that completes is bit-identical to its solo run) and zero
leaks (no shuffle files, no resources, no registered MemConsumers, no
service threads left behind).  Bounded well under 60s; runs in tier-1
(`-m soak` selects it alone)."""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.memory import MemManager
from blaze_tpu.plan.stages import DagScheduler
from blaze_tpu.serving import (QueryCancelled, QueryRejected, QueryService)

CONCURRENCY = 8
N_QUERIES = 40


@pytest.fixture(autouse=True)
def soak_env():
    faults.clear()
    MemManager.init(4 << 30)
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)  # staged path
    config.conf.set(config.TASK_RETRY_BACKOFF_MS.key, 1)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)
        config.conf.unset(config.TASK_RETRY_BACKOFF_MS.key)
        faults.clear()
        MemManager.init(4 << 30)


def _corpus(tmp_path):
    """Three small two-stage agg plans with distinct data + baselines."""
    plans = []
    for j, (n, n_keys) in enumerate([(4_000, 50), (6_000, 2_000),
                                     (3_000, 7)]):
        rng = np.random.default_rng(100 + j)
        t = pa.table({"k": pa.array(rng.integers(0, n_keys, n),
                                    type=pa.int64()),
                      "v": pa.array(rng.random(n))})
        paths = []
        for i in range(2):
            p = str(tmp_path / f"soak-{j}-{i}.parquet")
            pq.write_table(t.slice(i * (n // 2), n // 2), p)
            paths.append(p)
        schema = {"fields": [
            {"name": "k", "type": {"id": "int64"}, "nullable": True},
            {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
        plans.append({
            "kind": "hash_agg",
            "groupings": [{"expr": {"kind": "column", "index": 0},
                           "name": "k"}],
            "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                      "args": [{"kind": "column", "index": 1}]}],
            "input": {
                "kind": "local_exchange",
                "partitioning": {"kind": "hash",
                                 "exprs": [{"kind": "column",
                                            "index": 0}],
                                 "num_partitions": 3},
                "input": {
                    "kind": "hash_agg",
                    "groupings": [{"expr": {"kind": "column",
                                            "name": "k"}, "name": "k"}],
                    "aggs": [{"fn": "sum", "mode": "partial",
                              "name": "s",
                              "args": [{"kind": "column",
                                        "name": "v"}]}],
                    "input": {"kind": "parquet_scan", "schema": schema,
                              "file_groups": [[paths[0]],
                                              [paths[1]]]}}}})
    baselines = [DagScheduler().run_collect(p).to_pandas()
                 .sort_values("k").reset_index(drop=True) for p in plans]
    return plans, baselines


@pytest.mark.soak
def test_chaos_soak_concurrency8(tmp_path):
    plans, baselines = _corpus(tmp_path)
    rng = np.random.default_rng(42)
    t0 = time.monotonic()
    threads_before = {t.name for t in threading.enumerate()}

    svc = QueryService(max_concurrent=CONCURRENCY, max_queue=N_QUERIES,
                       tenant_max_inflight=N_QUERIES)
    submitted = []   # (handle, corpus index, expected-cancel?)
    shed = 0
    timers = []
    with faults.scoped(
            ("task-start", dict(p=0.05)),
            ("shuffle-read", dict(p=0.03)),
            ("admit", dict(p=0.05)),
            ("cancel-race", dict(p=0.5)),
            seed=7):
        for i in range(N_QUERIES):
            j = i % len(plans)
            deadline_ms = 0.0
            if i % 10 == 7:
                deadline_ms = float(rng.integers(1, 10))  # doomed-ish
            try:
                h = svc.submit(plans[j], tenant=f"t{i % 3}",
                               deadline_ms=deadline_ms)
            except QueryRejected as e:
                assert e.kind in ("injected", "queue-full",
                                  "tenant-quota")
                shed += 1
                continue
            expect_cancel = deadline_ms > 0
            if i % 9 == 4:
                expect_cancel = True
                tm = threading.Timer(float(rng.uniform(0.0, 0.05)),
                                     svc.cancel, args=(h.query_id,))
                tm.start()
                timers.append(tm)
            submitted.append((h, j, expect_cancel))

        outcomes = {"done": 0, "cancelled": 0, "failed": 0}
        for h, j, _expect in submitted:
            err = h.exception(timeout=60)
            outcomes[h.status] += 1
            if h.status == "done":
                # ZERO DIVERGENCE: every survivor bit-identical to solo
                got = (h.result().to_pandas().sort_values("k")
                       .reset_index(drop=True))
                assert got.equals(baselines[j]), \
                    f"divergent surviving query {h.query_id} (plan {j})"
            elif h.status == "cancelled":
                assert isinstance(err, QueryCancelled)
            else:
                # chaos may exhaust retries; the failure must be the
                # injected kind, never silent corruption
                assert isinstance(err, (faults.InjectedFault,
                                        faults.FetchFailedError)), err
            # ZERO LEAKS per query: scheduler post-mortem is clean.
            # (cancelled-while-queued queries never ran — no report)
            if h.status in ("done", "failed"):
                assert h.leak_report is not None, h.query_id
            if h.leak_report is not None:
                assert all(v == [] for v in h.leak_report.values()), \
                    (h.query_id, h.status, h.leak_report)

    for tm in timers:
        tm.cancel()
    stats = svc.stats()
    svc.shutdown(wait=True, cancel_running=True)

    # the run exercised every lane of the taxonomy
    assert outcomes["done"] >= N_QUERIES // 2, (outcomes, shed)
    assert outcomes["cancelled"] >= 1, (outcomes, shed)
    assert stats["counters"]["admitted"] == len(submitted)
    assert sum(outcomes.values()) == len(submitted)

    # ZERO LEAKS process-wide: consumers, service threads, temp files
    assert MemManager.get()._consumers == []
    for _ in range(50):  # pool threads wind down asynchronously
        alive = [t.name for t in threading.enumerate()
                 if t.name.startswith("blaze-serve")
                 and t.name not in threads_before]
        if not alive:
            break
        time.sleep(0.1)
    assert alive == [], alive
    leftovers = [f for f in os.listdir(str(tmp_path))
                 if not f.endswith(".parquet")]
    assert leftovers == [], leftovers

    assert time.monotonic() - t0 < 60, "soak exceeded its time budget"
