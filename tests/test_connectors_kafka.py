"""Connector + Kafka-path tests (ref thirdparty/auron-{iceberg,paimon} and
flink/kafka_scan_exec.rs mock-variant tests)."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import schema as S
from blaze_tpu.memory import MemManager


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


def test_iceberg_provider_with_deletes(tmp_path):
    from blaze_tpu.connectors import build_scan
    base = pa.table({"id": pa.array(range(100)),
                     "v": pa.array(np.arange(100) * 1.0)})
    data_path = str(tmp_path / "data.parquet")
    pq.write_table(base, data_path)
    # positional delete file: rows 3 and 7 of data.parquet
    pos = pa.table({"file_path": pa.array([data_path, data_path]),
                    "pos": pa.array([3, 7])})
    pos_path = str(tmp_path / "d1.pos.parquet")
    pq.write_table(pos, pos_path)
    # equality delete on id in {10, 11}
    eq = pa.table({"id": pa.array([10, 11])})
    eq_path = str(tmp_path / "d2.parquet")
    pq.write_table(eq, eq_path)
    desc = {"splits": [{"path": data_path,
                        "position_deletes": [pos_path],
                        "equality_deletes": [{"path": eq_path,
                                              "equality_ids": ["id"]}]}]}
    plan = build_scan("iceberg", desc, S.Schema.from_arrow(base.schema))
    got = plan.execute_collect().to_arrow()
    ids = got.column("id").to_pylist()
    assert len(ids) == 96
    for d in (3, 7, 10, 11):
        assert d not in ids


def test_paimon_provider_partition_values_and_dv(tmp_path):
    from blaze_tpu.connectors import build_scan
    base = pa.table({"id": pa.array(range(10))})
    p = str(tmp_path / "b.parquet")
    pq.write_table(base, p)
    schema = S.Schema([S.Field("id", S.INT64), S.Field("dt", S.UTF8)])
    desc = {"splits": [{"path": p,
                        "partition_values": {"dt": "2024-01-01"}}],
            "deletion_vectors": {p: [0, 9]}}
    plan = build_scan("paimon", desc, schema)
    got = plan.execute_collect().to_arrow()
    assert got.column("id").to_pylist() == list(range(1, 9))
    assert set(got.column("dt").to_pylist()) == {"2024-01-01"}


def test_hudi_provider_basic(tmp_path):
    from blaze_tpu.connectors import build_scan
    base = pa.table({"id": pa.array(range(5))})
    p = str(tmp_path / "h.parquet")
    pq.write_table(base, p)
    plan = build_scan("hudi", {"splits": [{"path": p}]},
                      S.Schema.from_arrow(base.schema))
    assert plan.execute_collect().num_rows == 5


def test_mock_kafka_json_scan():
    from blaze_tpu.ops.kafka import (JsonDeserializer, KafkaRecord,
                                     MockKafkaScanExec)
    schema = S.Schema([S.Field("k", S.UTF8), S.Field("n", S.INT64),
                       S.Field("x", S.FLOAT64)])
    recs = [KafkaRecord(json.dumps({"k": "a", "n": 1, "x": 0.5}).encode()),
            KafkaRecord(b"not json"),
            KafkaRecord(json.dumps({"k": "b", "n": "7"}).encode()),
            KafkaRecord(None)]
    scan = MockKafkaScanExec(schema, JsonDeserializer(schema), [recs])
    got = scan.execute_collect().to_arrow()
    assert got.column("k").to_pylist() == ["a", None, "b", None]
    assert got.column("n").to_pylist() == [1, None, 7, None]
    assert got.column("x").to_pylist() == [0.5, None, None, None]


def test_kafka_poll_callback_source():
    from blaze_tpu.bridge.resource import put_resource
    from blaze_tpu.ops.kafka import (JsonDeserializer, KafkaRecord,
                                     KafkaScanExec)
    schema = S.Schema([S.Field("n", S.INT64)])
    state = {"served": 0}

    def poll(partition, max_records):
        if state["served"] >= 3:
            return None
        state["served"] += 1
        return [KafkaRecord(json.dumps({"n": state["served"]}).encode())]

    put_resource("kafka-poll-1", poll)
    scan = KafkaScanExec(schema, JsonDeserializer(schema), "kafka-poll-1")
    got = scan.execute_collect().to_arrow()
    assert got.column("n").to_pylist() == [1, 2, 3]


def test_profiling_service_endpoints():
    import urllib.request
    from blaze_tpu.bridge.profiling import (record_metrics,
                                            start_http_service,
                                            stop_http_service)
    record_metrics({"name": "TestOp", "values": {"output_rows": 5},
                    "children": []})
    port = start_http_service()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=5) as r:
            status = json.loads(r.read())
        assert "mem_manager" in status
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            metrics = json.loads(r.read())
        assert any(m["name"] == "TestOp" for m in metrics)
    finally:
        stop_http_service()
