"""Fault-site conformance: every registered chaos site must be
exercised somewhere — by a test or by a bench chaos rule — so a new
site cannot land without coverage and a renamed site cannot silently
orphan its tests."""

import os
import re

from blaze_tpu import faults

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


def _corpus() -> str:
    chunks = []
    for name in sorted(os.listdir(_HERE)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        if name == os.path.basename(__file__):
            continue  # self-references must not count as coverage
        with open(os.path.join(_HERE, name)) as f:
            chunks.append(f.read())
    with open(os.path.join(_REPO, "bench.py")) as f:
        chunks.append(f.read())
    return "\n".join(chunks)


def test_every_fault_site_is_exercised():
    corpus = _corpus()
    missing = []
    for site in faults.SITES:
        # word-boundary safe for hyphenated site names: "worker-slow"
        # must not match inside "worker-slow-extra" or "x-worker-slow"
        if not re.search(rf"(?<![-\w]){re.escape(site)}(?![-\w])",
                         corpus):
            missing.append(site)
    assert not missing, (
        f"fault sites with no test or bench coverage: {missing} — add a "
        f"test exercising faults at the site (faults.scoped / "
        f"faults.configure) or a bench chaos rule naming it")


def test_sites_registry_matches_docstring():
    """The module docstring's site table is user-facing documentation;
    every registered site must appear in it."""
    doc = faults.__doc__ or ""
    undocumented = [s for s in faults.SITES if s not in doc]
    assert not undocumented, (
        f"sites missing from the faults module docstring: {undocumented}")
