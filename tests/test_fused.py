"""Fused-stage compiler tests: plan rewriting + result parity with the
eager AggExec path (plan/fused.py)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config
from blaze_tpu.exprs import BinaryExpr, col, lit
from blaze_tpu.ops import (AggExec, AggMode, FilterExec, MemoryScanExec,
                           make_agg)
from blaze_tpu.plan import create_plan
from blaze_tpu.plan.fused import FusedPartialAggExec, fuse_plan
from blaze_tpu.shuffle import HashPartitioning, LocalShuffleExchange


def _table(n=5000, seed=0, nulls=False):
    rng = np.random.default_rng(seed)
    cust = rng.integers(1, 200, n).astype(float)
    if nulls:
        mask = rng.random(n) < 0.05
        cust[mask] = np.nan
        cust_arr = pa.array(np.where(mask, None, cust).tolist(),
                            type=pa.int64())
    else:
        cust_arr = pa.array(cust.astype(np.int64))
    return pa.table({
        "date": pa.array(rng.integers(100, 200, n)),
        "cust": cust_arr,
        "store": pa.array(rng.integers(1, 13, n)),
        "amt": pa.array(np.round(rng.random(n) * 100, 2)),
    })


def _partial_agg_plan(scan):
    flt = FilterExec(scan, [BinaryExpr(">", col(0, "date"), lit(150))])
    return AggExec(flt,
                   [(col(1, "cust"), "cust"), (col(2, "store"), "store")],
                   [(make_agg("sum", [col(3)]), AggMode.PARTIAL, "amt_sum"),
                    (make_agg("count", [col(3)]), AggMode.PARTIAL, "cnt"),
                    (make_agg("min", [col(3)]), AggMode.PARTIAL, "amt_min"),
                    (make_agg("max", [col(3)]), AggMode.PARTIAL, "amt_max")])


def _collect(plan):
    out = [b.compact().to_arrow() for b in plan.execute(0)]
    out = [b for b in out if b.num_rows]
    t = pa.Table.from_batches(out, schema=plan.schema.to_arrow())
    df = t.to_pandas().sort_values(["cust", "store"]).reset_index(drop=True)
    return df


class TestDense:
    def test_memory_scan_fuses_dense_and_matches_eager(self):
        t = _table(nulls=True)
        eager = _partial_agg_plan(MemoryScanExec.from_arrow(t))
        fused = fuse_plan(_partial_agg_plan(MemoryScanExec.from_arrow(t)))
        assert isinstance(fused, FusedPartialAggExec)
        assert fused.fused_mode == "dense"
        a, b = _collect(eager), _collect(fused)
        assert len(a) == len(b)
        for c in a.columns:
            np.testing.assert_allclose(
                a[c].to_numpy(dtype=float), b[c].to_numpy(dtype=float),
                rtol=1e-9, err_msg=c)

    def test_parquet_stats_bounds(self, tmp_path):
        t = _table()
        path = str(tmp_path / "t.parquet")
        pq.write_table(t, path, row_group_size=1000)
        schema_d = {"fields": [
            {"name": "date", "type": {"id": "int64"}, "nullable": True},
            {"name": "cust", "type": {"id": "int64"}, "nullable": True},
            {"name": "store", "type": {"id": "int64"}, "nullable": True},
            {"name": "amt", "type": {"id": "float64"}, "nullable": True}]}
        d = {"kind": "hash_agg",
             "input": {"kind": "filter",
                       "input": {"kind": "parquet_scan", "schema": schema_d,
                                 "file_groups": [[path]]},
                       "predicates": [{"kind": "binary", "op": ">",
                                       "l": {"kind": "column",
                                             "name": "date"},
                                       "r": {"kind": "literal", "value": 150,
                                             "type": {"id": "int64"}}}]},
             "groupings": [{"expr": {"kind": "column", "name": "cust"},
                            "name": "cust"},
                           {"expr": {"kind": "column", "name": "store"},
                            "name": "store"}],
             "aggs": [{"fn": "sum", "mode": "partial", "name": "amt_sum",
                       "args": [{"kind": "column", "name": "amt"}]}]}
        eager = create_plan(d)
        fused = fuse_plan(create_plan(d))
        assert isinstance(fused, FusedPartialAggExec)
        assert fused.fused_mode == "dense"
        a, b = _collect(eager), _collect(fused)
        np.testing.assert_allclose(a["amt_sum.sum"].to_numpy(),
                                   b["amt_sum.sum"].to_numpy(), rtol=1e-9)

    def test_complete_mode_fuses(self):
        t = _table()
        scan = MemoryScanExec.from_arrow(t)
        agg = AggExec(scan, [(col(2, "store"), "store")],
                      [(make_agg("sum", [col(3)]), AggMode.COMPLETE, "s"),
                       (make_agg("count", [col(3)]), AggMode.COMPLETE, "c")])
        fused = fuse_plan(agg)
        assert isinstance(fused, FusedPartialAggExec)
        df = pa.Table.from_batches(
            [b.compact().to_arrow() for b in fused.execute(0)]).to_pandas()
        want = t.to_pandas().groupby("store").agg(
            s=("amt", "sum"), c=("amt", "count")).reset_index()
        got = df.sort_values("store").reset_index(drop=True)
        np.testing.assert_allclose(got["s"].to_numpy(),
                                   want["s"].to_numpy(), rtol=1e-9)
        assert (got["c"].to_numpy() == want["c"].to_numpy()).all()


class TestSorted:
    def _plan_with_computed_key(self, t):
        # group key is an arithmetic expr -> no traceable bounds -> sorted
        scan = MemoryScanExec.from_arrow(t)
        return AggExec(scan,
                       [(BinaryExpr("%", col(1, "cust"), lit(50)), "kmod")],
                       [(make_agg("sum", [col(3)]), AggMode.PARTIAL, "s")])

    def test_sorted_path_matches_eager(self):
        t = _table()
        eager = self._plan_with_computed_key(t)
        fused = fuse_plan(self._plan_with_computed_key(t))
        assert isinstance(fused, FusedPartialAggExec)
        assert fused.fused_mode == "sorted"
        a = pa.Table.from_batches([b.compact().to_arrow()
                                   for b in eager.execute(0)]).to_pandas()
        b = pa.Table.from_batches([b.compact().to_arrow()
                                   for b in fused.execute(0)]).to_pandas()
        a = a.sort_values("kmod").reset_index(drop=True)
        b = b.sort_values("kmod").reset_index(drop=True)
        np.testing.assert_allclose(a["s.sum"].to_numpy(),
                                   b["s.sum"].to_numpy(), rtol=1e-9)

    def test_overflow_degrades_to_passthrough_and_final_agg_fixes_it(self):
        t = _table(n=4000)
        config.conf.set(config.ON_DEVICE_AGG_CAPACITY.key, 16)
        # device hash-table mechanics under test: bypass the Arrow path
        config.conf.set(config.FUSED_HOST_VECTORIZED_ENABLE.key, False)
        try:
            partial = fuse_plan(self._plan_with_computed_key(t))
            assert partial.fused_mode == "sorted"
            ex = LocalShuffleExchange(partial,
                                      HashPartitioning([col(0)], 1))
            final = AggExec(ex, [(col(0, "kmod"), "kmod")],
                            [(make_agg("sum", [col(1)]),
                              AggMode.PARTIAL_MERGE, "s")])
            out = pa.Table.from_batches(
                [b.compact().to_arrow() for b in final.execute(0)]
            ).to_pandas().sort_values("kmod").reset_index(drop=True)
            assert int(partial.metrics.get("partial_skipped")) >= 1
        finally:
            config.conf.unset(config.ON_DEVICE_AGG_CAPACITY.key)
            config.conf.unset(config.FUSED_HOST_VECTORIZED_ENABLE.key)
        df = t.to_pandas()
        df["kmod"] = df.cust % 50
        want = df.groupby("kmod").amt.sum().reset_index() \
            .sort_values("kmod").reset_index(drop=True)
        np.testing.assert_allclose(out["s.sum"].to_numpy(),
                                   want["amt"].to_numpy(), rtol=1e-9)


class TestEligibility:
    def test_string_keys_fuse_onto_host_path(self):
        """utf8 group keys ride the host-vectorized fused path (Arrow's
        hash agg handles strings natively); the eager lexsort fallback
        dominated string-keyed queries.  Device strategies still require
        fixed-width keys (the fuse gate re-checks placement)."""
        t = pa.table({"s": pa.array(["a", "b", "a", None]),
                      "v": pa.array([1.0, 2.0, 3.0, 4.0])})
        agg = AggExec(MemoryScanExec.from_arrow(t),
                      [(col(0, "s"), "s")],
                      [(make_agg("sum", [col(1)]), AggMode.PARTIAL, "v")])
        fused = fuse_plan(agg)
        assert isinstance(fused, FusedPartialAggExec)
        out = fused.execute_collect().to_arrow()
        got = dict(zip(out.column(0).to_pylist(),
                       out.column(1).to_pylist()))
        assert got == {"a": 4.0, "b": 2.0, None: 4.0}

    def test_avg_not_fused(self):
        t = _table(n=100)
        agg = AggExec(MemoryScanExec.from_arrow(t),
                      [(col(2, "store"), "store")],
                      [(make_agg("avg", [col(3)]), AggMode.PARTIAL, "a")])
        assert not isinstance(fuse_plan(agg), FusedPartialAggExec)

    def test_mixed_modes_not_fused(self):
        t = _table(n=100)
        agg = AggExec(MemoryScanExec.from_arrow(t),
                      [(col(2, "store"), "store")],
                      [(make_agg("sum", [col(3)]), AggMode.PARTIAL, "s"),
                       (make_agg("count", [col(3)]), AggMode.FINAL, "c")])
        assert not isinstance(fuse_plan(agg), FusedPartialAggExec)


class TestMergeModeFusion:
    def _two_stage(self, t, partitions=2):
        partial = AggExec(MemoryScanExec.from_arrow(t),
                          [(col(1, "cust"), "cust")],
                          [(make_agg("sum", [col(3)]), AggMode.PARTIAL,
                            "s"),
                           (make_agg("count", [col(3)]), AggMode.PARTIAL,
                            "c")])
        ex = LocalShuffleExchange(partial,
                                  HashPartitioning([col(0)], partitions))
        final = AggExec(ex, [(col(0, "cust"), "cust")],
                        [(make_agg("sum", [col(1)]), AggMode.FINAL, "s"),
                         (make_agg("count", [col(2)]), AggMode.FINAL,
                          "c")])
        return final

    def test_final_mode_fuses_and_matches_pandas(self):
        t = _table(n=6000)
        plan = fuse_plan(self._two_stage(t))
        assert isinstance(plan, FusedPartialAggExec)
        assert plan.fused_mode == "sorted"
        out = []
        for p in range(plan.num_partitions):
            out.extend(b.compact().to_arrow() for b in plan.execute(p))
        got = pa.Table.from_batches([b for b in out if b.num_rows]) \
            .to_pandas().sort_values("cust").reset_index(drop=True)
        want = t.to_pandas().groupby("cust", as_index=False).agg(
            s=("amt", "sum"), c=("amt", "count")) \
            .sort_values("cust").reset_index(drop=True)
        assert len(got) == len(want)
        np.testing.assert_allclose(got.s.to_numpy(), want.s.to_numpy(),
                                   rtol=1e-9)
        assert (got.c.to_numpy() == want.c.to_numpy()).all()

    def test_final_mode_grows_instead_of_skipping(self):
        t = _table(n=6000)  # ~200 distinct cust per partition
        config.conf.set(config.ON_DEVICE_AGG_CAPACITY.key, 16)
        # this test exercises the DEVICE hash-table growth mechanics; the
        # host-vectorized Arrow path (default under host placement) never
        # builds that table
        config.conf.set(config.FUSED_HOST_VECTORIZED_ENABLE.key, False)
        try:
            plan = fuse_plan(self._two_stage(t, partitions=1))
            assert isinstance(plan, FusedPartialAggExec)
            out = [b.compact().to_arrow() for b in plan.execute(0)]
            got = pa.Table.from_batches([b for b in out if b.num_rows]) \
                .to_pandas().sort_values("cust").reset_index(drop=True)
            assert plan.metrics.get("table_grown") >= 1
            assert plan.metrics.get("partial_skipped") == 0
        finally:
            config.conf.unset(config.ON_DEVICE_AGG_CAPACITY.key)
            config.conf.unset(config.FUSED_HOST_VECTORIZED_ENABLE.key)
        want = t.to_pandas().groupby("cust", as_index=False).agg(
            s=("amt", "sum")).sort_values("cust").reset_index(drop=True)
        assert len(got) == len(want)
        np.testing.assert_allclose(got.s.to_numpy(), want.s.to_numpy(),
                                   rtol=1e-9)

    def test_config_gate(self):
        t = _table(n=100)
        config.conf.set(config.FUSED_STAGE_ENABLE.key, False)
        try:
            agg = _partial_agg_plan(MemoryScanExec.from_arrow(t))
            assert not isinstance(fuse_plan(agg), FusedPartialAggExec)
        finally:
            config.conf.unset(config.FUSED_STAGE_ENABLE.key)

    def test_inner_agg_rewritten_in_place(self):
        # the fused node must also be found under other operators
        t = _table(n=500)
        partial = _partial_agg_plan(MemoryScanExec.from_arrow(t))
        ex = LocalShuffleExchange(partial,
                                  HashPartitioning([col(0), col(1)], 2))
        final = AggExec(ex,
                        [(col(0, "cust"), "cust"), (col(1, "store"),
                                                    "store")],
                        [(make_agg("sum", [col(2)]), AggMode.PARTIAL_MERGE,
                          "amt_sum")])
        top = fuse_plan(final)
        # both stages fuse now: the top-level PARTIAL_MERGE and the inner
        # PARTIAL under the exchange
        assert isinstance(top, FusedPartialAggExec)
        assert isinstance(ex.children[0], FusedPartialAggExec)


class TestHostVectorized:
    """The Arrow C++ hash-agg path taken under host placement
    (plan/fused.py _execute_host_vectorized) must be bit-compatible with
    the device hash-table path across null keys, all-null sums, count
    modes and the merge threshold."""

    def _run(self, plan):
        out = []
        for p in range(plan.num_partitions):
            out.extend(b.compact().to_arrow() for b in plan.execute(p))
        return pa.Table.from_batches([b for b in out if b.num_rows])

    def test_matches_device_path_with_null_keys(self):
        t = _table(n=8000, nulls=True)
        def build():
            scan = MemoryScanExec.from_arrow(t)
            flt = FilterExec(scan, [BinaryExpr(">", col(0, "date"),
                                               lit(150))])
            return fuse_plan(AggExec(
                flt, [(col(1, "cust"), "cust")],
                [(make_agg("sum", [col(3)]), AggMode.PARTIAL, "s"),
                 (make_agg("count", [col(3)]), AggMode.PARTIAL, "c"),
                 (make_agg("min", [col(0)]), AggMode.PARTIAL, "mn"),
                 (make_agg("max", [col(0)]), AggMode.PARTIAL, "mx")]))
        host = self._run(build()).to_pandas().sort_values(
            "cust", na_position="first").reset_index(drop=True)
        config.conf.set(config.FUSED_HOST_VECTORIZED_ENABLE.key, False)
        try:
            dev = self._run(build()).to_pandas().sort_values(
                "cust", na_position="first").reset_index(drop=True)
        finally:
            config.conf.unset(config.FUSED_HOST_VECTORIZED_ENABLE.key)
        assert len(host) == len(dev)
        np.testing.assert_allclose(host["s.sum"].to_numpy(float),
                                   dev["s.sum"].to_numpy(float), rtol=1e-9)
        assert (host["c.count"].to_numpy() ==
                dev["c.count"].to_numpy()).all()
        assert (host["mn.min"].to_numpy(float) ==
                dev["mn.min"].to_numpy(float)).all()

    def test_merge_threshold_re_merges(self):
        # force the incremental acc-table merge by shrinking the buffer
        t = _table(n=5000)
        scan = MemoryScanExec.from_arrow(t, batch_rows=256)
        plan = fuse_plan(AggExec(
            scan, [(col(1, "cust"), "cust")],
            [(make_agg("sum", [col(3)]), AggMode.PARTIAL, "s")]))
        assert isinstance(plan, FusedPartialAggExec)
        config.conf.set(config.FUSED_HOST_COLLECT_ROWS.key, 512)
        try:
            got = self._run(plan).to_pandas()
        finally:
            config.conf.unset(config.FUSED_HOST_COLLECT_ROWS.key)
        got = got.groupby("cust", as_index=False)["s.sum"].sum() \
            .sort_values("cust").reset_index(drop=True)
        want = t.to_pandas().groupby("cust", as_index=False).amt.sum() \
            .sort_values("cust").reset_index(drop=True)
        np.testing.assert_allclose(got["s.sum"].to_numpy(),
                                   want["amt"].to_numpy(), rtol=1e-9)

    def test_float_keys_stay_on_device_path(self):
        t = pa.table({"k": pa.array([1.0, float("nan"), float("nan")]),
                      "v": pa.array([1.0, 2.0, 3.0])})
        plan = fuse_plan(AggExec(
            MemoryScanExec.from_arrow(t), [(col(0, "k"), "k")],
            [(make_agg("sum", [col(1)]), AggMode.PARTIAL, "s")]))
        assert isinstance(plan, FusedPartialAggExec)
        assert not plan._host_vectorized_eligible()
        # NaN keys group together (Spark NormalizeFloatingNumbers)
        out = self._run(plan)
        assert out.num_rows == 2


class TestHostPartialSkipping:
    def test_high_cardinality_partial_skips_and_final_fixes_it(self):
        """Host-vectorized PARTIAL agg over near-unique keys must degrade
        to pass-through (AGG_TRIGGER_PARTIAL_SKIPPING analog) while the
        FINAL stage still produces exact results."""
        import numpy as np
        n = 4000
        rng = np.random.default_rng(3)
        t = pa.table({"k": pa.array(np.arange(n)),  # all-distinct keys
                      "v": pa.array(rng.random(n))})
        config.conf.set(config.FUSED_HOST_COLLECT_ROWS.key, 512)
        config.conf.set(config.PARTIAL_AGG_SKIPPING_MIN_ROWS.key, 256)
        try:
            partial = fuse_plan(AggExec(
                MemoryScanExec.from_arrow(t, batch_rows=256),
                [(col(0, "k"), "k")],
                [(make_agg("sum", [col(1)]), AggMode.PARTIAL, "s"),
                 (make_agg("count", [col(1)]), AggMode.PARTIAL, "c")]))
            assert isinstance(partial, FusedPartialAggExec)
            ex = LocalShuffleExchange(partial,
                                      HashPartitioning([col(0)], 2))
            final = AggExec(ex, [(col(0, "k"), "k")],
                            [(make_agg("sum", [col(1)]), AggMode.FINAL,
                              "s"),
                             (make_agg("count", [col(2)]), AggMode.FINAL,
                              "c")])
            out = []
            for p in range(2):
                out.extend(b.compact().to_arrow()
                           for b in final.execute(p))
            got = pa.Table.from_batches(
                [b for b in out if b.num_rows]).to_pandas() \
                .sort_values("k").reset_index(drop=True)
            assert int(partial.metrics.get("partial_skipped") or 0) >= 1
        finally:
            config.conf.unset(config.FUSED_HOST_COLLECT_ROWS.key)
            config.conf.unset(config.PARTIAL_AGG_SKIPPING_MIN_ROWS.key)
        want = t.to_pandas().groupby("k", as_index=False).agg(
            s=("v", "sum"), c=("v", "count")).sort_values("k") \
            .reset_index(drop=True)
        assert len(got) == len(want)
        np.testing.assert_allclose(got.s.to_numpy(), want.s.to_numpy(),
                                   rtol=1e-9)
        assert (got.c.to_numpy() == want.c.to_numpy()).all()
