"""Critical-path bottleneck attribution (bridge/critical_path.py),
the explain-analyze footer built on it, and live query progress
(serving/progress.py) + the `tools.top` renderer.
"""

import pytest

from blaze_tpu.bridge import critical_path
from blaze_tpu.plan.explain import format_bottleneck_footer
from blaze_tpu.serving import progress

_MS = 1_000_000


def _span(name, t0_ms, dur_ms, sid=1, parent=None, **attrs):
    t0, dur = t0_ms * _MS, dur_ms * _MS
    r = {"name": name, "t0_ns": t0, "t1_ns": t0 + dur, "dur_ns": dur,
         "sid": sid, "thread": "t", "attrs": dict(attrs)}
    if parent is not None:
        r["parent"] = parent
    return r


# -- attribution -------------------------------------------------------------

def test_categories_sum_to_wall_exactly():
    spans = [
        _span("admission_wait", 0, 50, sid=1),
        _span("task", 50, 300, sid=2),
        _span("shuffle_exchange", 100, 80, sid=3),   # inside the task
        _span("stage_loop_chunk", 200, 60, sid=4),   # inside the task
        _span("operator:ParquetScanExec", 260, 30, sid=5),
        # 350..400 uncovered, then a final exchange
        _span("device_exchange", 400, 100, sid=6),
    ]
    att = critical_path.attribute(spans)
    total = sum(att[c] for c in critical_path.CATEGORIES)
    assert total == pytest.approx(att["wall_s"], rel=1e-9)
    assert att["wall_s"] == pytest.approx(0.500)
    assert att["admission_wait"] == pytest.approx(0.050)
    # exchange beats the covering task span (priority order)
    assert att["exchange_wire"] == pytest.approx(0.180)
    assert att["device_compute"] == pytest.approx(0.060)
    assert att["scan_decode"] == pytest.approx(0.030)
    assert att["host_compute"] == pytest.approx(0.130)
    # the uncovered 50ms precedes an exchange segment -> barrier
    assert att["barrier_idle"] == pytest.approx(0.050)
    assert att["dispatch_gap"] == 0.0


def test_uncovered_gap_not_before_exchange_is_dispatch_gap():
    spans = [_span("task", 0, 100), _span("task", 200, 100, sid=2)]
    att = critical_path.attribute(spans)
    assert att["dispatch_gap"] == pytest.approx(0.100)
    assert att["barrier_idle"] == 0.0


def test_xla_compile_instant_counts_its_ns_attr():
    spans = [{"name": "xla_compile", "t0_ns": 0, "t1_ns": 0, "dur_ns": 0,
              "sid": 1, "attrs": {"ns": 100 * _MS}},
             _span("task", 100, 100, sid=2)]
    att = critical_path.attribute(spans)
    assert att["device_compute"] == pytest.approx(0.100)


def test_malformed_spans_are_skipped_not_fatal():
    spans = [None, 42, {"name": 7}, {"name": "task", "t0_ns": "x"},
             _span("task", 0, 10)]
    att = critical_path.attribute(spans)
    assert att["host_compute"] == pytest.approx(0.010)


def test_report_none_without_usable_spans():
    assert critical_path.bottleneck_report([]) is None
    assert critical_path.bottleneck_report(
        [{"name": "task", "t0_ns": 5, "t1_ns": 5, "dur_ns": 0}]) is None


def test_report_shape_and_dominant():
    spans = [_span("task", 0, 100), _span("device_exchange", 0, 80, sid=2)]
    rep = critical_path.bottleneck_report(spans, wall_s=0.11)
    assert rep["v"] == 1
    assert rep["dominant"] == "exchange_wire"
    assert rep["dominant_fraction"] == pytest.approx(0.8)
    assert rep["query_wall_s"] == pytest.approx(0.11)
    assert sum(rep["categories"].values()) == pytest.approx(rep["wall_s"])


def test_critical_path_descends_longest_children():
    spans = [
        _span("task", 0, 300, sid=1),
        _span("operator:AggExec", 0, 100, sid=2, parent=1),
        _span("operator:ParquetScanExec", 100, 180, sid=3, parent=1),
    ]
    path = critical_path.critical_path(spans)
    assert [e["name"] for e in path] == \
        ["task", "operator:ParquetScanExec"]
    assert path[1]["category"] == "scan_decode"


# -- explain footer ----------------------------------------------------------

def test_footer_none_keeps_disabled_path_identical():
    assert format_bottleneck_footer(None) is None
    assert format_bottleneck_footer({"span_count": 0}) is None


def test_footer_renders_dominant_and_categories():
    rep = critical_path.bottleneck_report(
        [_span("task", 0, 100), _span("device_exchange", 0, 80, sid=2)])
    line = format_bottleneck_footer(rep)
    assert line.startswith("bottleneck: wall=0.100s")
    assert "dominant=exchange_wire (80%)" in line
    assert "host_compute=0.020s" in line


# -- live progress -----------------------------------------------------------

@pytest.fixture(autouse=True)
def fresh_progress():
    progress.reset()
    yield
    progress.reset()


def test_progress_lifecycle_and_rates():
    progress.note_query_start("q1", fingerprint="fp", prior_wall_s=10.0)
    progress.note_stage_start("q1", 0, 4)
    progress.note_task_done("q1", 0)
    progress.note_rows("q1", 0, rows=100, bytes_=1000)
    p = progress.progress("q1")
    assert p["state"] == "running"
    assert p["tasks_done"] == 1 and p["tasks_total"] == 4
    assert p["rows"] == 100 and p["bytes"] == 1000
    assert p["eta_source"] == "prior"  # prior wins while one exists
    assert 0.0 <= p["eta_s"] <= 10.0
    progress.note_query_done("q1", "finished", wall_s=0.5)
    done = progress.progress("q1")
    assert done["state"] == "done" and done["status"] == "finished"
    assert done["elapsed_s"] == pytest.approx(0.5)
    snap = progress.snapshot_all()
    assert snap["running"] == []
    assert [q["query_id"] for q in snap["recent"]] == ["q1"]


def test_progress_fraction_eta_without_prior():
    progress.note_query_start("q2")
    progress.note_stage_start("q2", 0, 10)
    for _ in range(5):
        progress.note_task_done("q2", 0)
    p = progress.progress("q2")
    assert p["eta_source"] == "fraction"
    assert p["eta_s"] is not None and p["eta_s"] >= 0.0


def test_progress_unknown_query_is_none():
    assert progress.progress("nope") is None


def test_progress_stage_reentry_accumulates_totals():
    progress.note_query_start("q3")
    progress.note_stage_start("q3", 0, 2)
    progress.note_stage_start("q3", 0, 1)  # recovery re-entry
    assert progress.progress("q3")["tasks_total"] == 3


# -- tools.top renderer ------------------------------------------------------

def test_top_render_table_and_serving_line():
    from blaze_tpu.tools import top
    progress.note_query_start("q4", prior_wall_s=2.0)
    progress.note_stage_start("q4", 0, 2)
    progress.note_task_done("q4", 0)
    snap = progress.snapshot_all()
    serving = {"services": [
        {"queue_depth": 1, "running": 2, "max_concurrent": 4,
         "max_queue": 16, "counters": {},
         "tenants": {"acme": {"completed": 7, "p50_ms": 1.0,
                              "p99_ms": 2.0}}}]}
    text = top.render(snap, serving)
    assert "QUERY" in text and "q4" in text
    assert "0/1" in text   # stages column
    assert "1/2" in text   # tasks column
    assert "serving: running=2 queued=1 completed=7 services=1" in text
