"""Stage-DAG scheduler tests: whole multi-stage plans (with exchanges)
executed task-by-task over the protobuf wire (VERDICT r2 #3 — the
production path: plan split -> TaskDefinition bytes -> NativeExecutionRuntime
-> shuffle files -> ipc_reader)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.itest import generate
from blaze_tpu.itest.queries import QUERIES
from blaze_tpu.itest.runner import compare_frames
from blaze_tpu.itest.tpcds_data import write_parquet_splits
from blaze_tpu.memory import MemManager
from blaze_tpu.plan.stages import DagScheduler


@pytest.fixture(autouse=True)
def budget():
    MemManager.init(4 << 30)


@pytest.fixture(autouse=True)
def staged_path():
    """These tests assert the STAGED wire machinery; disable the AQE
    small-query local mode so tiny fixtures still split into stages."""
    from blaze_tpu import config
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


def _table_from(got: pa.Table) -> pd.DataFrame:
    return got.to_pandas() if got.num_rows else pd.DataFrame(
        {n: [] for n in got.schema.names})


def test_two_stage_agg_over_wire(tmp_path):
    rng = np.random.default_rng(7)
    n = 30_000
    t = pa.table({"k": pa.array(rng.integers(0, 500, n), type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    paths = []
    for i in range(2):
        p = str(tmp_path / f"in-{i}.parquet")
        pq.write_table(t.slice(i * (n // 2), n // 2), p)
        paths.append(p)
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    plan = {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": 3},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": {"kind": "parquet_scan", "schema": schema,
                          "file_groups": [[paths[0]], [paths[1]]]}}}}
    sched = DagScheduler(work_dir=str(tmp_path / "dag"))
    got = sched.run_collect(plan).to_pandas()
    assert len(sched.stages) == 2
    assert sched.stages[0].num_tasks == 2      # two map splits
    assert sched.stages[-1].num_tasks == 3     # three reducers
    want = t.to_pandas().groupby("k", as_index=False).v.sum() \
        .rename(columns={"v": "s"})
    got = got.sort_values("k").reset_index(drop=True)
    want = want.sort_values("k").reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_allclose(got["s"].to_numpy(), want["s"].to_numpy(),
                               rtol=1e-9)


@pytest.mark.parametrize("qname", ["q01", "q06", "q95"])
def test_tpcds_query_over_wire(qname, tmp_path):
    """The itest queries run through the FULL wire path: stage split,
    per-task proto TaskDefinitions, shuffle files, block-map readers."""
    builder, table_names = QUERIES[qname]
    tables = generate(table_names, scale=0.2)
    paths = write_parquet_splits(tables, str(tmp_path), 2)
    plan_dict, oracle = builder(paths, tables, 2)
    got = DagScheduler(work_dir=str(tmp_path / "dag")).run_collect(
        plan_dict)
    err = compare_frames(_table_from(got), oracle())
    assert err is None, f"{qname}: {err}"


def test_broadcast_build_over_exchange(tmp_path):
    """A broadcast join whose BUILD side contains an exchange: every task
    must see ALL build rows (BroadcastJoinExec pulls every partition of
    its build child's ipc_reader)."""
    import uuid as _uuid
    rng = np.random.default_rng(11)
    n = 8_000
    fact = pa.table({"k": pa.array(rng.integers(0, 40, n), type=pa.int64()),
                     "v": pa.array(rng.random(n))})
    fpaths = []
    for i in range(2):
        p = str(tmp_path / f"fact-{i}.parquet")
        pq.write_table(fact.slice(i * (n // 2), n // 2), p)
        fpaths.append(p)
    dim = pa.table({"k": pa.array(np.arange(40), type=pa.int64()),
                    "w": pa.array(rng.random(40))})
    dpath = str(tmp_path / "dim.parquet")
    pq.write_table(dim, dpath)
    fschema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    dschema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "w", "type": {"id": "float64"}, "nullable": True}]}
    # build side: dim scan -> partial/final agg pair over an exchange
    build = {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "w",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": 3},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "w",
                          "args": [{"kind": "column", "name": "w"}]}],
                "input": {"kind": "parquet_scan", "schema": dschema,
                          "file_groups": [[dpath]]}}}}
    plan = {
        "kind": "hash_agg",
        "groupings": [],
        "aggs": [{"fn": "count", "mode": "partial", "name": "cnt",
                  "args": [{"kind": "column", "index": 0}]},
                 {"fn": "sum", "mode": "partial", "name": "wsum",
                  "args": [{"kind": "column", "index": 3}]}],
        "input": {"kind": "broadcast_join", "join_type": "inner",
                  "left": {"kind": "parquet_scan", "schema": fschema,
                           "file_groups": [[fpaths[0]], [fpaths[1]]]},
                  "right": build,
                  "left_keys": [{"kind": "column", "index": 0}],
                  "right_keys": [{"kind": "column", "index": 0}],
                  "build_side": "right",
                  "broadcast_id": f"t-{_uuid.uuid4().hex[:8]}"}}
    got = DagScheduler(work_dir=str(tmp_path / "dag")).run_collect(plan)
    df = got.to_pandas()  # one partial row per result task
    f = fact.to_pandas()
    d = dim.to_pandas().groupby("k", as_index=False).w.sum()
    j = f.merge(d, on="k")
    assert int(df.iloc[:, 0].sum()) == len(j)  # every fact row matched once
    np.testing.assert_allclose(float(df.iloc[:, 1].sum()),
                               float(j.w.sum()), rtol=1e-9)
