"""Cross-query work sharing: plan fingerprints + source snapshots, the
bounded result/subplan cache (invalidation on source mutation, snapshot
advance), single-flight dedup (coalesce, winner-cancelled promotion),
the shared scan-decode broker, admission accounting of cached bytes,
and the off-by-default zero-overhead contract."""

import os
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import config, faults
from blaze_tpu.bridge import xla_stats
from blaze_tpu.cache import get_cache, reset_cache
from blaze_tpu.cache.scanshare import (ScanBroker, follow_batches,
                                       get_broker)
from blaze_tpu.memory import MemManager
from blaze_tpu.plan import fingerprint as fp
from blaze_tpu.plan.explain import format_work_sharing_footer
from blaze_tpu.plan.stages import DagScheduler
from blaze_tpu.serving import QueryCancelled, QueryRejected, QueryService


@pytest.fixture(autouse=True)
def clean_slate():
    faults.clear()
    MemManager.init(4 << 30)
    reset_cache()
    try:
        yield
    finally:
        reset_cache()
        faults.clear()
        MemManager.init(4 << 30)


@pytest.fixture
def cache_on():
    config.conf.set(config.CACHE_ENABLE.key, True)
    try:
        yield
    finally:
        config.conf.unset(config.CACHE_ENABLE.key)


@pytest.fixture
def single_flight_on():
    config.conf.set(config.SERVING_SINGLE_FLIGHT.key, True)
    try:
        yield
    finally:
        config.conf.unset(config.SERVING_SINGLE_FLIGHT.key)


@pytest.fixture
def staged_path():
    config.conf.set(config.DAG_SINGLE_TASK_BYTES.key, 0)
    try:
        yield
    finally:
        config.conf.unset(config.DAG_SINGLE_TASK_BYTES.key)


def _delta(before):
    after = xla_stats.cache_stats()
    return {k: after[k] - before.get(k, 0) for k in after
            if after[k] != before.get(k, 0)}


def _write_table(path, n=2_000, seed=7, n_keys=50):
    rng = np.random.default_rng(seed)
    t = pa.table({"k": pa.array(rng.integers(0, n_keys, n),
                                type=pa.int64()),
                  "v": pa.array(rng.random(n))})
    pq.write_table(t, path)
    return t


def _scan_plan(paths):
    schema = {"fields": [
        {"name": "k", "type": {"id": "int64"}, "nullable": True},
        {"name": "v", "type": {"id": "float64"}, "nullable": True}]}
    return {"kind": "parquet_scan", "schema": schema,
            "file_groups": [[p] for p in paths]}


def _agg_plan(paths, n_reduce=3):
    return {
        "kind": "hash_agg",
        "groupings": [{"expr": {"kind": "column", "index": 0},
                       "name": "k"}],
        "aggs": [{"fn": "sum", "mode": "final", "name": "s",
                  "args": [{"kind": "column", "index": 1}]}],
        "input": {
            "kind": "local_exchange",
            "partitioning": {"kind": "hash",
                             "exprs": [{"kind": "column", "index": 0}],
                             "num_partitions": n_reduce},
            "input": {
                "kind": "hash_agg",
                "groupings": [{"expr": {"kind": "column", "name": "k"},
                               "name": "k"}],
                "aggs": [{"fn": "sum", "mode": "partial", "name": "s",
                          "args": [{"kind": "column", "name": "v"}]}],
                "input": _scan_plan(paths)}}}


def _sorted(tbl):
    return tbl.sort_by([("k", "ascending")])


# -- fingerprints & snapshots ------------------------------------------------

def test_fingerprint_stable_under_key_order(tmp_path):
    p = str(tmp_path / "a.parquet")
    _write_table(p)
    plan = _scan_plan([p])
    # same logical plan, different dict insertion order
    reordered = {k: plan[k] for k in reversed(list(plan))}
    assert fp.plan_fingerprint(plan) == fp.plan_fingerprint(reordered)
    other = dict(plan, extra_knob=1)
    assert fp.plan_fingerprint(plan) != fp.plan_fingerprint(other)


def test_source_snapshot_uncacheable_plans(tmp_path):
    # no version signal: memory scans cannot be validated
    assert fp.source_snapshot({"kind": "memory_scan", "rid": "r1"}) \
        is None
    # run-scoped readers never collide across queries
    assert fp.result_cache_key(
        {"kind": "hash_agg", "input": {"kind": "ipc_reader",
                                       "rid": "stage://1/0"}}) is None
    # un-stat-able file: no invalidation evidence, never cached
    gone = _scan_plan([str(tmp_path / "missing.parquet")])
    assert fp.source_snapshot(gone) is None
    # no versioned source at all
    assert fp.source_snapshot({"kind": "empty"}) is None


def test_source_snapshot_tracks_mtime_and_snapshot_id(tmp_path):
    p = str(tmp_path / "a.parquet")
    _write_table(p)
    plan = _scan_plan([p])
    snap1 = fp.source_snapshot(plan)
    assert p in snap1["files"]
    # rewrite + explicit mtime bump (filesystems can be coarse)
    _write_table(p, seed=8)
    st = os.stat(p)
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    snap2 = fp.source_snapshot(plan)
    assert fp.snapshot_digest(snap1) != fp.snapshot_digest(snap2)
    # a connector-stamped snapshot_id (Iceberg analog) versions too
    tagged = dict(plan, snapshot_id=41)
    advanced = dict(plan, snapshot_id=42)
    assert fp.source_snapshot(tagged)["snapshots"] == ["41"]
    assert (fp.snapshot_digest(fp.source_snapshot(tagged))
            != fp.snapshot_digest(fp.source_snapshot(advanced)))


# -- result cache ------------------------------------------------------------

def test_result_cache_invalidates_on_snapshot_mismatch(cache_on):
    cache = get_cache()
    t = pa.table({"x": pa.array([1, 2, 3], type=pa.int64())})
    snap_a = {"files": {"f": [1, 10]}, "snapshots": []}
    snap_b = {"files": {"f": [2, 10]}, "snapshots": []}  # mtime advanced
    assert cache.put_result("fp1", snap_a, t)
    assert cache.get_result("fp1", snap_a).equals(t)
    before = xla_stats.cache_stats()
    assert cache.get_result("fp1", snap_b) is None  # stale: evicted
    d = _delta(before)
    assert d.get("result_cache_invalidations") == 1
    assert cache.stats()["entries"] == 0
    # the stale entry is gone even for the original snapshot
    assert cache.peek_result_nbytes("fp1", snap_a) is None


def test_result_cache_byte_budget_evicts_lru(cache_on):
    reset_cache()
    config.conf.set(config.CACHE_MAX_BYTES.key, 1 << 14)
    try:
        cache = get_cache()
        snap = {"files": {"f": [1, 1]}, "snapshots": []}
        big = pa.table({"x": pa.array(np.arange(500), type=pa.int64())})
        for i in range(8):
            assert cache.put_result(f"fp{i}", snap, big)
        s = cache.stats()
        assert s["used_bytes"] <= s["max_bytes"]
        assert s["entries"] < 8  # LRU shed the oldest
        assert cache.peek_result_nbytes("fp7", snap) is not None
    finally:
        config.conf.unset(config.CACHE_MAX_BYTES.key)
        reset_cache()


def test_mem_pressure_spill_halves_footprint(cache_on):
    cache = get_cache()
    snap = {"files": {"f": [1, 1]}, "snapshots": []}
    big = pa.table({"x": pa.array(np.arange(4096), type=pa.int64())})
    for i in range(4):
        cache.put_result(f"fp{i}", snap, big)
    used = cache.stats()["used_bytes"]
    released = cache.spill()
    assert released >= used // 2
    assert cache.stats()["used_bytes"] <= used // 2
    assert cache.mem_used == cache.stats()["used_bytes"]


def test_service_invalidates_on_source_mutation(tmp_path, cache_on):
    p = str(tmp_path / "a.parquet")
    _write_table(p, seed=7)
    plan = _agg_plan([p])
    svc = QueryService(max_concurrent=2, max_queue=8)
    try:
        r1 = svc.submit(plan).result(30)
        r2 = svc.submit(plan).result(30)
        assert r1.equals(r2)  # bit-identical hit
        assert svc.counters["cache_hits"] == 1
        # mutate the source: rewrite + guaranteed mtime advance
        _write_table(p, seed=99)
        st = os.stat(p)
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
        before = xla_stats.cache_stats()
        r3 = svc.submit(plan).result(30)
        d = _delta(before)
        assert d.get("result_cache_invalidations", 0) >= 1
        assert not _sorted(r3).equals(_sorted(r1))  # fresh data served
        assert svc.counters["cache_hits"] == 1  # no stale hit
    finally:
        svc.shutdown()


# -- single-flight dedup -----------------------------------------------------

def test_single_flight_coalesces_identical_queries(tmp_path,
                                                   single_flight_on):
    p = str(tmp_path / "a.parquet")
    _write_table(p)
    plan = _scan_plan([p])
    gate = threading.Event()
    runs = []

    def slow(plan, ctx, handle):
        runs.append(handle.query_id)
        gate.wait(10)
        ctx.check()
        return pa.table({"n": pa.array([len(runs)], type=pa.int64())})

    svc = QueryService(max_concurrent=4, max_queue=16, executor=slow)
    try:
        before = xla_stats.cache_stats()
        handles = [svc.submit(plan) for _ in range(5)]
        time.sleep(0.2)
        gate.set()
        results = [h.result(10) for h in handles]
        assert len(runs) == 1  # one execution, five answers
        assert svc.counters["coalesced"] == 4
        assert all(r.equals(results[0]) for r in results)
        assert _delta(before).get("single_flight_coalesces") == 4
        assert all(h.status == "done" for h in handles)
    finally:
        svc.shutdown()


def test_winner_cancelled_promotes_waiter(tmp_path, single_flight_on,
                                          cache_on):
    p = str(tmp_path / "a.parquet")
    _write_table(p)
    plan = _scan_plan([p])
    done = threading.Event()
    started = []

    def slow(plan, ctx, handle):
        started.append(handle.query_id)
        while not done.wait(0.02):
            ctx.check()
        ctx.check()
        return pa.table({"n": pa.array([7], type=pa.int64())})

    svc = QueryService(max_concurrent=2, max_queue=16, executor=slow)
    try:
        leader = svc.submit(plan)
        time.sleep(0.1)
        w1 = svc.submit(plan)
        w2 = svc.submit(plan)
        time.sleep(0.1)
        before = xla_stats.cache_stats()
        leader.cancel("caller went away")
        time.sleep(0.3)  # leader notices, promotion runs
        done.set()
        # the leader's cancellation stays its own
        with pytest.raises(QueryCancelled, match="caller went away"):
            leader.result(10)
        # a promoted waiter re-ran the work; both waiters got the answer
        assert w1.result(10).num_rows == 1
        assert w2.result(10).num_rows == 1
        assert len(started) == 2  # leader + exactly one promoted waiter
        assert _delta(before).get("single_flight_promotions") == 1
        # the cancelled winner never poisoned the cache: a fresh submit
        # hits the PROMOTED run's stored result
        r = svc.submit(plan).result(10)
        assert r.num_rows == 1
        assert svc.counters["cache_hits"] == 1
    finally:
        svc.shutdown()


# -- subplan cache -----------------------------------------------------------

def test_subplan_cache_hit_bit_identical(tmp_path, cache_on,
                                         staged_path):
    p0, p1 = str(tmp_path / "a0.parquet"), str(tmp_path / "a1.parquet")
    _write_table(p0, seed=1)
    _write_table(p1, seed=2)
    plan = _agg_plan([p0, p1])
    before = xla_stats.cache_stats()
    r1 = DagScheduler().run_collect(plan)
    d1 = _delta(before)
    assert d1.get("subplan_cache_puts", 0) >= 1
    before = xla_stats.cache_stats()
    r2 = DagScheduler().run_collect(plan)
    d2 = _delta(before)
    assert d2.get("subplan_cache_hits", 0) >= 1
    assert _sorted(r2).equals(_sorted(r1))


def test_subplan_cache_replay_is_chaos_immune(tmp_path, cache_on,
                                              staged_path):
    p = str(tmp_path / "a.parquet")
    _write_table(p)
    plan = _agg_plan([p])
    r1 = DagScheduler().run_collect(plan)  # populates the cache
    # every shuffle read would now fail — but cached replays hand the
    # reducers raw bytes blocks, which never touch the fetch path
    faults.install("shuffle-read", p=1.0)
    r2 = DagScheduler().run_collect(plan)
    assert _sorted(r2).equals(_sorted(r1))
    assert faults.stats().get("shuffle-read",
                              {"fires": 0})["fires"] == 0


# -- scan-decode broker ------------------------------------------------------

def test_scan_broker_lease_follow_release():
    b = ScanBroker()
    role, lead = b.lease("/f.parquet", [0, 1], ["k", "v"], 8192)
    assert role == "lead"
    # subset columns ride the leader's superset; exact key must match
    role2, e2 = b.lease("/f.parquet", [0, 1], ["k"], 8192)
    assert role2 == "follow" and e2 is lead
    # different row groups never share
    role3, e3 = b.lease("/f.parquet", [0], ["k"], 8192)
    assert role3 == "lead" and e3 is not lead
    # wider columns than the leader cannot follow it
    role4, e4 = b.lease("/f.parquet", [0, 1], None, 8192)
    assert role4 == "lead" and e4 is not lead
    batches = [pa.record_batch([pa.array([1, 2])], names=["k"])]
    before = xla_stats.cache_stats()
    b.publish(lead, batches)
    got = follow_batches(e2)
    assert got is batches
    d = _delta(before)
    assert d.get("scan_share_hits") == 1
    assert d.get("scan_share_bytes_saved", 0) > 0
    for e in (lead, e2, e3, e4):
        b.release(e)
    assert b.live_entries() == 0


def test_scan_broker_leader_error_falls_back():
    b = ScanBroker()
    _, lead = b.lease("/f.parquet", [0], ["k"], 8192)
    _, follower = b.lease("/f.parquet", [0], ["k"], 8192)
    b.publish(lead, None, error=RuntimeError("decode blew up"))
    # the follower decodes itself instead of surfacing a foreign error
    assert follow_batches(follower) is None
    # errored entries are never joined by later arrivals
    role, fresh = b.lease("/f.parquet", [0], ["k"], 8192)
    assert role == "lead" and fresh is not lead
    for e in (lead, follower, fresh):
        b.release(e)
    assert b.live_entries() == 0


def test_scan_share_concurrent_runs_bit_identical(tmp_path, cache_on):
    config.conf.set(config.CACHE_SCAN_SHARE.key, True)
    try:
        p = str(tmp_path / "a.parquet")
        _write_table(p, n=5_000)
        plan = _scan_plan([p])
        fresh = DagScheduler().run_collect(plan)
        results, errors = [None] * 6, []
        barrier = threading.Barrier(6)

        def run(i):
            try:
                barrier.wait(10)
                results[i] = DagScheduler().run_collect(plan)
            except BaseException as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(6)]
        before = xla_stats.cache_stats()
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors
        assert all(r.equals(fresh) for r in results)
        d = _delta(before)
        assert d.get("scan_share_misses", 0) >= 1  # someone led
        assert get_broker().live_entries() == 0  # nothing retained
    finally:
        config.conf.unset(config.CACHE_SCAN_SHARE.key)


# -- admission gate ----------------------------------------------------------

def test_admission_gate_accounts_cached_result_bytes(tmp_path,
                                                     cache_on):
    p = str(tmp_path / "big.parquet")
    _write_table(p, n=60_000)
    assert os.path.getsize(p) > 64 << 10
    plan = _agg_plan([p])
    # prime the cache through a permissive service
    warm = QueryService(max_concurrent=2, max_queue=8,
                        admit_mem_bytes=1 << 30)
    try:
        cached = warm.submit(plan).result(30)
    finally:
        warm.shutdown()
    # a strict gate sheds the cold scan estimate...
    svc = QueryService(max_concurrent=2, max_queue=8,
                       admit_mem_bytes=64 << 10)
    try:
        cold = dict(plan, extra_knob=1)  # same bytes, no cache entry
        with pytest.raises(QueryRejected, match="memory"):
            svc.submit(cold)
        # ...but the cached plan admits on its materialized footprint
        h = svc.submit(plan)
        assert h.result(30).equals(cached)
        assert svc.counters["cache_hits"] == 1
        assert svc.counters["shed_memory"] == 1
    finally:
        svc.shutdown()


# -- off-by-default contract -------------------------------------------------

def test_cache_disabled_by_default_zero_overhead(tmp_path):
    assert config.CACHE_ENABLE.get() is False
    assert get_cache() is None  # disabled path allocates nothing
    p = str(tmp_path / "a.parquet")
    _write_table(p)
    plan = _agg_plan([p])
    before = xla_stats.cache_stats()
    svc = QueryService(max_concurrent=2, max_queue=8)
    try:
        r1 = svc.submit(plan).result(30)
        r2 = svc.submit(plan).result(30)
    finally:
        svc.shutdown()
    assert r1.equals(r2)  # both executions ran fresh, byte-identical
    assert _delta(before) == {}  # not a single cache counter moved
    assert svc.counters["cache_hits"] == 0
    assert svc.counters["coalesced"] == 0
    # the explain footer stays silent when nothing was shared
    assert format_work_sharing_footer(
        {k: 0 for k in xla_stats.cache_stats()}) is None


def test_cache_hits_emit_trace_instants(tmp_path, cache_on,
                                        staged_path):
    from blaze_tpu.bridge import tracing
    p = str(tmp_path / "a.parquet")
    _write_table(p)
    plan = _agg_plan([p])
    tracing.start_tracing()
    try:
        DagScheduler().run_collect(plan)  # populate
        DagScheduler().run_collect(plan)  # subplan_cache_hit instant
        svc = QueryService(max_concurrent=2, max_queue=8)
        try:
            svc.submit(plan).result(30)  # populate the result ring
            svc.submit(plan).result(30)  # result_cache_hit instant
        finally:
            svc.shutdown()
        names = [s["name"] for s in tracing.spans()]
        assert "subplan_cache_hit" in names
        assert "result_cache_hit" in names
    finally:
        tracing.stop_tracing()
        with tracing._lock:
            tracing._spans.clear()
        tracing.reset_conf_probe()


def test_work_sharing_footer_renders_only_when_active():
    zeros = {k: 0 for k in xla_stats.cache_stats()}
    assert format_work_sharing_footer(zeros) is None
    active = dict(zeros, result_cache_hits=3, result_cache_misses=1)
    line = format_work_sharing_footer(active)
    assert line is not None and "work sharing" in line
    assert "3/4" in line
