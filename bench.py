"""Benchmark: TPC-DS q01-shaped pipeline on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload (BASELINE.md config #1 shape): store_returns-like table,
filter on date key -> group by (customer, store) -> sum(return_amt) +
count — the inner aggregation of TPC-DS q01.

Engine path measured: the DENSE-GROUP-ID fast path (parallel/stage.py
pack_dense_keys + dense_partial_agg) — grouping keys with known bounds
(parquet min/max stats or dictionary codes) pack into one id and the
whole pipeline is filter + three fused scatter-reduces; no device sort.
This is the planner's hot path for bounded-key aggregations; the
sort-based table (partial_agg_table) remains the unbounded fallback.

Baseline: the same filter+groupby through pyarrow's C++ vectorized
kernels on the host CPU — the stand-in for Auron's CPU-native columnar
engine (the repo-published Auron numbers are cluster-scale TPC-DS 1TB
means, recorded in BASELINE.md, not reproducible here).  vs_baseline is
TPU wall-clock speedup over that CPU columnar engine on identical data,
median of 5 runs, excluding compile (both engines warm).  Correctness is
asserted against the host result every run.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_ROWS = 8_000_000
CUTOFF = 2450500
CUSTOMERS = 50_000
STORES = 12


def make_data(n_rows: int = N_ROWS, seed: int = 0):
    rng = np.random.default_rng(seed)
    return {
        "sr_returned_date_sk": rng.integers(2450000, 2451000, n_rows),
        "sr_customer_sk": rng.integers(1, CUSTOMERS + 1, n_rows),
        "sr_store_sk": rng.integers(1, STORES + 1, n_rows),
        "sr_return_amt": np.round(rng.random(n_rows) * 500, 2),
    }


def cpu_baseline(data, iters: int = 3):
    import pyarrow as pa
    t = pa.table(data)

    def run():
        import pyarrow.compute as pc
        mask = pc.greater(t.column("sr_returned_date_sk"), CUTOFF)
        f = t.filter(mask)
        return f.group_by(["sr_customer_sk", "sr_store_sk"]).aggregate(
            [("sr_return_amt", "sum"), ("sr_return_amt", "count")])

    out = run()  # warm
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = run()
        times.append(time.perf_counter() - t0)
    return out, float(np.median(times))


def tpu_run(data, iters: int = 5):
    import jax
    import jax.numpy as jnp
    from blaze_tpu.parallel.stage import (dense_partial_agg,
                                          pack_dense_keys)

    ranges = [(1, CUSTOMERS), (1, STORES)]

    @jax.jit
    def pipeline(date_sk, cust, store, amt):
        valid = date_sk > CUTOFF
        ones = jnp.ones_like(valid)
        gid, num_slots = pack_dense_keys(
            [(cust, ones), (store, ones)], ranges)
        accs, avalid, occupied = dense_partial_agg(
            gid, num_slots,
            [("sum", amt, None), ("count", None, None)], valid)
        return accs[0], accs[1], occupied

    cols = (jnp.asarray(data["sr_returned_date_sk"]),
            jnp.asarray(data["sr_customer_sk"]),
            jnp.asarray(data["sr_store_sk"]),
            jnp.asarray(data["sr_return_amt"]))
    out = pipeline(*cols)
    jax.block_until_ready(out)  # compile + warm
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = pipeline(*cols)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return out, float(np.median(times))


def main():
    data = make_data()
    cpu_out, cpu_s = cpu_baseline(data)
    (sums, counts, occupied), tpu_s = tpu_run(data)

    # correctness vs the host engine
    occ = np.asarray(occupied)
    got_groups = int(occ.sum())
    got_sum = float(np.asarray(sums)[occ].sum())
    got_count = int(np.asarray(counts)[occ].sum())
    want_groups = cpu_out.num_rows
    want_sum = float(np.asarray(cpu_out.column("sr_return_amt_sum")).sum())
    want_count = int(np.asarray(
        cpu_out.column("sr_return_amt_count")).sum())
    assert got_groups == want_groups, (got_groups, want_groups)
    assert got_count == want_count, (got_count, want_count)
    assert abs(got_sum - want_sum) / max(abs(want_sum), 1) < 1e-9, \
        (got_sum, want_sum)

    rows_per_sec = N_ROWS / tpu_s
    print(json.dumps({
        "metric": "tpcds_q01_shaped_agg_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(cpu_s / tpu_s, 3),
    }))


if __name__ == "__main__":
    main()
