"""Benchmark: TPC-DS q01-shaped pipeline on one TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Workload (BASELINE.md config #1 shape): store_returns-like table,
filter on date key -> group by (customer, store) -> sum(return_amt) +
count, two-phase (partial tables per batch, device merge) — the same
shape as TPC-DS q01's inner aggregation at SF1 (~288K store_returns rows;
we run a few SF to get stable timing).

Baseline: the same pipeline through pyarrow's C++ vectorized groupby on
the host CPU — the stand-in for Auron's CPU-native columnar engine
(the repo-published Auron numbers are cluster-scale TPC-DS 1TB means,
not reproducible here; BASELINE.md records them).  vs_baseline is the
wall-clock speedup of the TPU stage over that CPU columnar baseline on
identical data.  Correctness is asserted against the same host result.
"""

from __future__ import annotations

import json
import time

import numpy as np


def make_data(n_rows: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    return {
        "sr_returned_date_sk": rng.integers(2450000, 2451000, n_rows),
        "sr_customer_sk": rng.integers(1, 50_000, n_rows),
        "sr_store_sk": rng.integers(1, 13, n_rows),
        "sr_return_amt": np.round(rng.random(n_rows) * 500, 2),
    }


def cpu_baseline(data, cutoff):
    import pyarrow as pa
    import pyarrow.compute as pc
    t = pa.table(data)
    t0 = time.perf_counter()
    mask = pc.greater(t.column("sr_returned_date_sk"), cutoff)
    f = t.filter(mask)
    out = f.group_by(["sr_customer_sk", "sr_store_sk"]).aggregate(
        [("sr_return_amt", "sum"), ("sr_return_amt", "count")])
    elapsed = time.perf_counter() - t0
    return out, elapsed


def tpu_run(data, cutoff, batch_rows=1 << 20, num_slots=1 << 20):
    import jax
    import jax.numpy as jnp
    from blaze_tpu.parallel.stage import (AggTable, merge_agg_tables,
                                          partial_agg_table)

    n = len(data["sr_return_amt"])
    n_batches = -(-n // batch_rows)

    @jax.jit
    def stage(date_sk, cust, store, amt):
        ones = jnp.ones(date_sk.shape[0], dtype=bool)
        valid = date_sk > cutoff
        return partial_agg_table(
            [(cust, ones), (store, ones)],
            [("sum", amt, ones), ("count", None, None)],
            valid, num_slots=num_slots)

    @jax.jit
    def merge_all(*tables):
        cat = AggTable(
            tuple(jnp.concatenate(cols) for cols in
                  zip(*(t.keys for t in tables))),
            tuple(jnp.concatenate(cols) for cols in
                  zip(*(t.key_valid for t in tables))),
            tuple(jnp.concatenate(cols) for cols in
                  zip(*(t.accs for t in tables))),
            tuple(jnp.concatenate(cols) for cols in
                  zip(*(t.acc_valid for t in tables))),
            jnp.concatenate([t.slot_valid for t in tables]),
            sum(t.num_groups for t in tables))
        return merge_agg_tables(cat, ["sum", "count"], num_slots)

    # stage batches
    batches = []
    for off in range(0, n, batch_rows):
        end = min(off + batch_rows, n)
        pad = batch_rows - (end - off)
        def col(name):
            a = data[name][off:end]
            if pad:
                a = np.concatenate([a, np.zeros(pad, dtype=a.dtype)])
            return jnp.asarray(a)
        batches.append((col("sr_returned_date_sk"),
                        col("sr_customer_sk"),
                        col("sr_store_sk"),
                        col("sr_return_amt")))

    # warm up compiles (cached afterwards)
    warm = [stage(*batches[0])] * n_batches
    jax.block_until_ready(merge_all(*warm))

    t0 = time.perf_counter()
    tables = [stage(*b) for b in batches]
    acc = merge_all(*tables)
    jax.block_until_ready(acc)
    elapsed = time.perf_counter() - t0
    # overflow guard: the general spilling path handles it in the engine;
    # the fused bench shape must fit its static table
    assert int(acc.num_groups) <= num_slots, "bench table overflow"
    return acc, elapsed


def main():
    n_rows = 8_000_000  # ~SF28-equivalent store_returns volume
    cutoff = 2450500
    data = make_data(n_rows)

    cpu_out, cpu_s = cpu_baseline(data, cutoff)
    tpu_out, tpu_s = tpu_run(data, cutoff)

    # correctness: same group count and total sum
    slot_valid = np.asarray(tpu_out.slot_valid)
    got_groups = int(slot_valid.sum())
    got_sum = float(np.asarray(tpu_out.accs[0])[slot_valid].sum())
    want_groups = cpu_out.num_rows
    want_sum = float(np.asarray(cpu_out.column("sr_return_amt_sum")).sum())
    assert got_groups == want_groups, (got_groups, want_groups)
    assert abs(got_sum - want_sum) / max(abs(want_sum), 1) < 1e-9, \
        (got_sum, want_sum)

    rows_per_sec = n_rows / tpu_s
    print(json.dumps({
        "metric": "tpcds_q01_shaped_agg_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(cpu_s / tpu_s, 3),
    }))


if __name__ == "__main__":
    main()
